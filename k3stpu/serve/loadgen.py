"""Concurrent-client load generator for the inference server.

Measures what the micro-batcher exists to improve: aggregate examples/s and
per-request latency when N clients hit /v1/predict at once. Run it twice —
``--batch-window-ms 0`` (each request its own device dispatch, the
pre-coalescing behavior) vs the default window — and the delta is the
committed before/after artifact (the reference proves its stack with logged
oracles the same way, reference README.md:128-156).

Self-hosting mode (default) starts the server in-process on a free port so
one command produces a number on any box (CPU CI or a TPU pod):

    python -m k3stpu.serve.loadgen --model transformer --clients 8 \
        --seconds 10 --batch-window-ms 5

Point it at a live server instead with --url http://host:8096, or at a
fleet with --endpoints http://a:8096,http://b:8096 (replicas for the
client-side spread, or ONE router URL for the routed comparison) — the
result then breaks p50/p95/p99 out per replica, keyed by each
response's X-K3STPU-Replica header.
Emits one LOADGEN_JSON line (pod-log interface, like the probe).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error

import numpy as np

from k3stpu.obs import (TraceBuffer, format_traceparent, new_span_id,
                        new_trace_id)

_MAX_ERRORS_PER_CLIENT = 10

# 503 retry policy (the server's containment layer — breaker open, drain,
# watchdog trip — answers 503 + Retry-After; see docs/RESILIENCE.md).
# Backoff honors Retry-After, else exponential from _BACKOFF_BASE_S,
# capped at _BACKOFF_CAP_S, always jittered to avoid client lockstep.
_MAX_RETRIES_503 = 8
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 2.0


class ClientTraces:
    """Client-side half of the distributed trace, shared by all client
    threads. Every logical request mints a W3C trace id (kept stable
    across its 503 retries — the whole backoff chain correlates to ONE
    id on the server), gets client-side spans in a ``TraceBuffer``
    (exported as a Chrome trace for trace_merge.py), and leaves a
    ``rid``↔trace-id record — failures marked — so a bad load-test
    request can be looked up directly in the server's /debug/trace."""

    def __init__(self, capacity: int = 4096):
        self.buf = TraceBuffer(capacity=capacity, component="client")
        self._records: "list[dict]" = []
        self._lock = threading.Lock()

    def start(self, trace_id: str):
        return self.buf.start(trace_id=trace_id)

    def finish(self, tr, ok: bool, latency_s: "float | None",
               ttft_s: "float | None", attempts: int,
               error: "str | None" = None,
               replica: "str | None" = None) -> None:
        rec = {"rid": tr.rid, "trace_id": tr.trace_id, "ok": ok,
               "attempts": attempts}
        if latency_s is not None:
            rec["latency_ms"] = round(latency_s * 1e3, 3)
        if ttft_s is not None:
            rec["ttft_ms"] = round(ttft_s * 1e3, 3)
        if error is not None:
            rec["error"] = error
        if replica is not None:
            rec["replica"] = replica
        with self._lock:
            self._records.append(rec)
        tr.finish("ok" if ok else "error", error)

    def records(self) -> "list[dict]":
        with self._lock:
            return list(self._records)

    def chrome_trace(self) -> dict:
        return self.buf.chrome_trace()


class ArrivalRecorder:
    """--record-arrivals: one record per LOGICAL request (503 retries
    collapse into their first try) in the simulator's trace schema
    (``k3stpu/sim/traces.py``, ``k3stpu-sim-trace-v1``), so real
    captured traffic replays through the digital twin unchanged.

    ``t`` is seconds since the first recorded arrival — the sim's
    virtual epoch. Prompt shape/class/session come from the request
    payload itself (parsed once per note; the payload is what the
    server would have seen, so the trace can't drift from the load)."""

    SCHEMA = "k3stpu-sim-trace-v1"

    def __init__(self):
        self._lock = threading.Lock()
        self._t0: "float | None" = None
        self._requests: "list[dict]" = []

    def note(self, t_perf: float, payload: bytes) -> None:
        try:
            body = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            body = {}
        pt = body.get("prompt_tokens")
        if isinstance(pt, list) and pt and isinstance(pt[0], list):
            prompt_tokens = len(pt[0])
        else:
            # /v1/predict shapes: rows of feature vectors, no prompt.
            inputs = body.get("inputs")
            prompt_tokens = len(inputs) if isinstance(inputs, list) else 0
        rec = {
            "priority": body.get("priority", "interactive"),
            "prompt_tokens": prompt_tokens,
            "max_new_tokens": int(body.get("max_new_tokens", 0)),
            "session": body.get("session"),
        }
        with self._lock:
            if self._t0 is None:
                self._t0 = t_perf
            rec["t"] = round(max(0.0, t_perf - self._t0), 6)
            self._requests.append(rec)

    def trace(self) -> dict:
        with self._lock:
            reqs = sorted(self._requests, key=lambda r: r["t"])
        return {"schema": self.SCHEMA, "requests": reqs}

    def dump(self, path: str) -> int:
        doc = self.trace()
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        return len(doc["requests"])


def _gen_prompt(rows: int) -> "list[int]":
    """THE generate-load prompt — deterministic and shared by the warmup
    and the measured load, so the warmed prefill program (and, with
    --prompt-cache, the cached row) is exactly the one the load hits:
    the measured window then shows the steady state, not one stray
    compile/miss."""
    rng = np.random.default_rng(0)
    return rng.integers(1, 1000, size=(max(4, rows),)).tolist()


def _client_loop(url: str, payload: bytes, stop: "threading.Event",
                 latencies: list, lock: "threading.Lock", errors: list,
                 route: str = "/v1/predict", ttfts: "list | None" = None,
                 retry_stats: "dict | None" = None, seed: int = 0,
                 traces: "ClientTraces | None" = None,
                 recorder: "ArrivalRecorder | None" = None):
    """``ttfts`` non-None switches to SSE consumption: the request body
    carries ``"stream": true`` and the client records time-to-first-token
    (first ``data:`` frame) alongside the full-response latency — the
    pair is the streaming story: TTFT ~ prefill latency while total
    stays the full decode.

    ``retry_stats`` non-None ({"retries": 0, "gave_up": 0}, shared under
    ``lock``) turns on 503 retries: backoff honoring Retry-After, capped
    exponential otherwise, jittered by a per-client ``seed`` RNG so the
    retry schedule is deterministic per client but never in lockstep
    across clients.

    Every logical request carries a ``traceparent``: one trace id for
    its whole life (503 retries INCLUDED — each retry is a new span id
    under the same trace, so the server-side 503 echoes and the final
    success all correlate), recorded in ``traces`` when given.

    Each success records which replica served it (the
    ``X-K3STPU-Replica`` response header — passed through by the router
    tier, so this works one hop or two): ``latencies`` entries are
    ``(latency_s, replica | None)`` pairs and ``traces`` records gain a
    ``replica`` field, feeding the per-replica percentile report."""
    import urllib.request

    rng = random.Random(seed)
    attempt = 0  # consecutive 503s on the CURRENT request
    my_errors = 0
    trace_id = None
    tr = None
    t_first_try = None

    def _finish(ok, latency_s, ttft_s, error=None, replica=None):
        if tr is not None:
            traces.finish(tr, ok, latency_s, ttft_s, attempt + 1,
                          error=error, replica=replica)

    while not stop.is_set():
        if trace_id is None:  # new logical request, not a 503 retry
            trace_id = new_trace_id()
            tr = traces.start(trace_id) if traces is not None else None
            t_first_try = time.perf_counter()
            if recorder is not None:
                recorder.note(t_first_try, payload)
        req = urllib.request.Request(
            url + route, data=payload,
            headers={"Content-Type": "application/json",
                     "traceparent": format_traceparent(trace_id,
                                                       new_span_id())})
        t0 = time.perf_counter()
        replica = None
        try:
            with urllib.request.urlopen(req, timeout=300) as r:
                replica = r.headers.get("X-K3STPU-Replica")
                if tr is not None:
                    tr.t_admit = tr.event("response_headers")
                if ttfts is None:
                    json.loads(r.read())
                    ttft = None
                else:
                    ttft = None
                    last = None
                    for line in r:  # SSE frames, EOF-delimited
                        if not line.startswith(b"data: "):
                            continue
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                            if tr is not None:
                                tr.t_first = tr.event("first_token")
                        last = json.loads(line[6:])
                    # A truncated stream (no done frame) is a failure
                    # too — counting it as success would understate
                    # latency and overstate tokens/s.
                    if last is None or "error" in last \
                            or not last.get("done"):
                        raise RuntimeError(
                            f"stream ended badly: {last}")
        except Exception as e:  # noqa: BLE001 — record, don't kill the run
            if (retry_stats is not None
                    and isinstance(e, urllib.error.HTTPError)
                    and e.code == 503):
                attempt += 1
                if attempt <= _MAX_RETRIES_503:
                    try:
                        ra = float(e.headers.get("Retry-After"))
                    except (TypeError, ValueError):
                        ra = 0.0
                    sleep = min(_BACKOFF_CAP_S,
                                max(ra, _BACKOFF_BASE_S * 2 ** attempt))
                    with lock:
                        retry_stats["retries"] += 1
                    if tr is not None:
                        tr.event("retry_503", {"attempt": attempt,
                                               "backoff_s": round(sleep, 3)})
                    stop.wait(sleep * (0.5 + rng.random()))
                    continue  # does NOT count toward _MAX_ERRORS_PER_CLIENT
                with lock:
                    retry_stats["gave_up"] += 1
                e = RuntimeError(
                    f"503 persisted through {_MAX_RETRIES_503} retries: {e}")
            _finish(False, time.perf_counter() - t_first_try, None,
                    error=str(e))
            trace_id = tr = None
            attempt = 0
            with lock:
                errors.append(str(e))
            my_errors += 1
            if my_errors >= _MAX_ERRORS_PER_CLIENT:
                return  # persistently failing client stops; others continue
            continue
        latency = time.perf_counter() - t0
        _finish(True, latency, ttft, replica=replica)
        trace_id = tr = None
        attempt = 0
        my_errors = 0  # consecutive-failure counter: success resets it
        with lock:
            latencies.append((latency, replica))
            if ttft is not None:
                ttfts.append(ttft)


def run_load(url: "str | list[str]", *, clients: int, seconds: float,
             rows: int, input_shape: "tuple[int, ...]", input_dtype: str,
             generate_tokens: int = 0, stream: bool = False,
             traces: "ClientTraces | None" = None,
             recorder: "ArrivalRecorder | None" = None) -> dict:
    """``generate_tokens > 0`` switches to /v1/generate load (each request
    one ragged prompt, ``generate_tokens`` new tokens) — the decode-loop
    workload the continuous-batching engine schedules. ``stream`` rides
    the SSE route and adds time-to-first-token percentiles.

    ``url`` may be a list (--endpoints): client i sticks to endpoint
    ``i % len(urls)`` for its whole run — the dumb client-side spread the
    router tier is measured against. Either way, every success is
    attributed to the replica named by its ``X-K3STPU-Replica`` header
    and the result carries per-replica percentiles alongside the
    aggregate."""
    urls = [url] if isinstance(url, str) else list(url)
    rng = np.random.default_rng(0)
    ttfts: "list[float] | None" = None
    if generate_tokens > 0:
        body = {"prompt_tokens": [_gen_prompt(rows)],
                "max_new_tokens": generate_tokens}
        if stream:
            body["stream"] = True
            ttfts = []
        payload = json.dumps(body).encode()
        route = "/v1/generate"
    else:
        if input_dtype == "int32":
            block = rng.integers(0, 1000, size=(rows, *input_shape),
                                 dtype=np.int32)
        else:
            block = rng.standard_normal(
                (rows, *input_shape)).astype(np.float32)
        payload = json.dumps({"inputs": block.tolist()}).encode()
        route = "/v1/predict"

    latencies: "list[tuple[float, str | None]]" = []
    errors: list[str] = []
    retry_stats = {"retries": 0, "gave_up": 0}
    lock = threading.Lock()
    stop = threading.Event()
    threads = [threading.Thread(
        target=_client_loop,
        args=(urls[i % len(urls)], payload, stop, latencies, lock,
              errors, route, ttfts, retry_stats, i, traces, recorder),
        daemon=True)
        for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=300)
    wall = time.perf_counter() - t0

    if not latencies:
        raise RuntimeError(f"no request succeeded; errors: {errors[:3]}")

    def pct(sorted_ms: "list[float]", q: float) -> float:
        return sorted_ms[min(len(sorted_ms) - 1, int(q * len(sorted_ms)))]

    lat_ms = sorted(1e3 * l for l, _ in latencies)
    pick = lambda q: pct(lat_ms, q)
    out = {
        "clients": clients,
        "endpoints": len(urls),
        "rows_per_request": rows,
        "wall_s": round(wall, 2),
        "requests": len(lat_ms),
        "errors": len(errors),  # transient failures don't void the run
        "retries_503": retry_stats["retries"],
        "gave_up_503": retry_stats["gave_up"],
        "examples": len(lat_ms) * rows,
        "examples_per_s": round(len(lat_ms) * rows / wall, 2),
        "p50_ms": round(pick(0.50), 2),
        "p95_ms": round(pick(0.95), 2),
        "p99_ms": round(pick(0.99), 2),
    }
    if generate_tokens > 0:
        out["gen_tokens_per_request"] = generate_tokens
        out["client_tokens_per_s"] = round(
            len(lat_ms) * generate_tokens / wall, 2)
    if ttfts:
        tt = sorted(1e3 * t for t in ttfts)
        out["ttft_p50_ms"] = round(pct(tt, 0.50), 2)
        out["ttft_p95_ms"] = round(pct(tt, 0.95), 2)
        out["ttft_p99_ms"] = round(pct(tt, 0.99), 2)
    by_replica: "dict[str, list[float]]" = {}
    for lat, rep in latencies:
        if rep is not None:
            by_replica.setdefault(rep, []).append(1e3 * lat)
    if by_replica:
        out["per_replica"] = {
            rep: {"requests": len(ms),
                  "p50_ms": round(pct(sorted(ms), 0.50), 2),
                  "p95_ms": round(pct(sorted(ms), 0.95), 2),
                  "p99_ms": round(pct(sorted(ms), 0.99), 2)}
            for rep, ms in sorted(by_replica.items())}
    return out


def parse_mix(spec: str) -> "tuple[int, int]":
    """``--mix`` spec → (short_weight, long_weight).

    Spec: ``short:long=<w>:<w>`` — e.g. ``short:long=9:1`` is nine
    short requests for every long one. Both weights must be positive
    integers; the class names are fixed (they name the two payloads the
    mixed mode builds, not arbitrary traffic classes)."""
    try:
        names, _, weights = spec.partition("=")
        if names != "short:long":
            raise ValueError(spec)
        w_short_s, w_long_s = weights.split(":")
        w_short, w_long = int(w_short_s), int(w_long_s)
    except ValueError:
        raise ValueError(
            f"bad mix spec {spec!r} (want e.g. 'short:long=9:1')") from None
    if w_short < 1 or w_long < 1:
        raise ValueError(f"mix weights must be >= 1, got {spec!r}")
    return w_short, w_long


def run_mixed(url: "str | list[str]", *, clients: int, seconds: float,
              mix: "tuple[int, int]", rows: int, long_rows: int,
              generate_tokens: int,
              traces: "ClientTraces | None" = None,
              recorder: "ArrivalRecorder | None" = None) -> dict:
    """Mixed short/long traffic against /v1/generate — the disagg
    workload (docs/DISAGG.md): long prompts are the prefill
    interference that inflates short requests' inter-token latency on
    a monolithic replica, and the number this mode exists to expose is
    the SHORT class's TPOT tail under that interference.

    The client pool splits by the mix weights (each class keeps at
    least one client; short rounds up — it is the measured class).
    Both classes ride the SSE route so every request observes TTFT;
    TPOT is the post-first-token decode rate,
    ``(latency - ttft) / (generate_tokens - 1)``. The result carries
    per-class TTFT and TPOT p50/p95/p99 under ``classes``.

    QoS mapping (docs/QOS.md): short requests are ``interactive``, long
    requests ``batch`` — the payloads carry the ``priority`` field
    always (a classless server validates and ignores it), so the same
    mixed run exercises class-weighted admission, batch-first shedding,
    and preemption when pointed at a --qos fleet."""
    if generate_tokens < 2:
        raise ValueError("mixed mode needs --generate-tokens >= 2 "
                         "(TPOT is defined past the first token)")
    urls = [url] if isinstance(url, str) else list(url)
    w_short, w_long = mix
    n_long = max(1, round(clients * w_long / (w_short + w_long)))
    n_short = max(1, clients - n_long)
    specs = [("short", n_short, _gen_prompt(rows), "interactive"),
             ("long", n_long, _gen_prompt(long_rows), "batch")]

    lock = threading.Lock()
    stop = threading.Event()
    retry_stats = {"retries": 0, "gave_up": 0}
    per_class: "dict[str, dict]" = {}
    threads = []
    seed = 0
    for tag, n, prompt, priority in specs:
        payload = json.dumps({"prompt_tokens": [prompt],
                              "max_new_tokens": generate_tokens,
                              "priority": priority,
                              "stream": True}).encode()
        cls = {"latencies": [], "ttfts": [], "errors": [],
               "clients": n, "prompt_tokens": len(prompt),
               "priority": priority}
        per_class[tag] = cls
        for _ in range(n):
            threads.append(threading.Thread(
                target=_client_loop,
                args=(urls[seed % len(urls)], payload, stop,
                      cls["latencies"], lock, cls["errors"],
                      "/v1/generate", cls["ttfts"], retry_stats, seed,
                      traces, recorder),
                daemon=True))
            seed += 1
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=300)
    wall = time.perf_counter() - t0

    if not per_class["short"]["latencies"]:
        raise RuntimeError(
            f"no short request succeeded; errors: "
            f"{(per_class['short']['errors'] + per_class['long']['errors'])[:3]}")

    def pct(sorted_ms: "list[float]", q: float) -> float:
        return sorted_ms[min(len(sorted_ms) - 1, int(q * len(sorted_ms)))]

    classes = {}
    all_lat_ms: "list[float]" = []
    total_errors = 0
    for tag, _, _, _ in specs:
        cls = per_class[tag]
        # latencies and ttfts append in the same locked block per
        # success, so they are index-aligned pairs.
        lats = [l for l, _ in cls["latencies"]]
        tpots = [1e3 * (lat - tt) / (generate_tokens - 1)
                 for lat, tt in zip(lats, cls["ttfts"])]
        lat_ms = sorted(1e3 * l for l in lats)
        tt_ms = sorted(1e3 * t for t in cls["ttfts"])
        tpots.sort()
        doc = {"clients": cls["clients"],
               "prompt_tokens": cls["prompt_tokens"],
               "priority": cls["priority"],
               "requests": len(lat_ms),
               "errors": len(cls["errors"])}
        if lat_ms:
            for q, label in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                doc[f"ttft_{label}_ms"] = round(pct(tt_ms, q), 2)
                doc[f"tpot_{label}_ms"] = round(pct(tpots, q), 3)
                doc[f"{label}_ms"] = round(pct(lat_ms, q), 2)
        classes[tag] = doc
        all_lat_ms.extend(lat_ms)
        total_errors += len(cls["errors"])
    all_lat_ms.sort()
    return {
        "mix": f"short:long={w_short}:{w_long}",
        "clients": n_short + n_long,
        "endpoints": len(urls),
        "gen_tokens_per_request": generate_tokens,
        "wall_s": round(wall, 2),
        "requests": len(all_lat_ms),
        "errors": total_errors,
        "retries_503": retry_stats["retries"],
        "gave_up_503": retry_stats["gave_up"],
        "p50_ms": round(pct(all_lat_ms, 0.50), 2),
        "p95_ms": round(pct(all_lat_ms, 0.95), 2),
        "p99_ms": round(pct(all_lat_ms, 0.99), 2),
        "classes": classes,
    }


def parse_ramp(spec: str, base_clients: int) -> "list[tuple[int, float]]":
    """``--ramp`` spec → [(clients, seconds), ...] phases.

    Spec: comma-separated ``<mult>x:<seconds>s`` phases, multipliers of
    ``--clients`` — e.g. ``1x:30s,4x:60s,1x:30s`` is 30 s at base load,
    a 4x surge for 60 s, then back. Fractional multipliers are allowed
    (``0.5x:10s``); each phase must round to at least one client."""
    phases = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            mult_s, dur_s = part.split(":")
            if not mult_s.endswith("x") or not dur_s.endswith("s"):
                raise ValueError(part)
            mult = float(mult_s[:-1])
            dur = float(dur_s[:-1])
        except ValueError:
            raise ValueError(
                f"bad ramp phase {part!r} (want e.g. '4x:60s')") from None
        clients = max(1, round(mult * base_clients))
        if dur <= 0:
            raise ValueError(f"ramp phase {part!r}: duration must be > 0")
        phases.append((clients, dur))
    if not phases:
        raise ValueError(f"empty ramp spec {spec!r}")
    return phases


def run_ramp(url: "str | list[str]", *, phases: "list[tuple[int, float]]",
             rows: int, input_shape: "tuple[int, ...]", input_dtype: str,
             generate_tokens: int = 0, stream: bool = False,
             traces: "ClientTraces | None" = None,
             recorder: "ArrivalRecorder | None" = None) -> dict:
    """Piecewise-constant load: each (clients, seconds) phase runs its
    own client pool to completion (threads started, run, stopped, and
    JOINED per phase — in-flight requests finish before the next phase
    starts, so every request attributes to exactly one phase). The
    surge-and-recede shape is the autoscaler's test signal: phase-level
    p50/p95/p99 show whether the fleet grew fast enough to hold the
    surge and whether the shrink gave anything back."""
    urls = [url] if isinstance(url, str) else list(url)
    rng = np.random.default_rng(0)
    ttfts_wanted = stream and generate_tokens > 0
    if generate_tokens > 0:
        body = {"prompt_tokens": [_gen_prompt(rows)],
                "max_new_tokens": generate_tokens}
        if stream:
            body["stream"] = True
        payload = json.dumps(body).encode()
        route = "/v1/generate"
    else:
        if input_dtype == "int32":
            block = rng.integers(0, 1000, size=(rows, *input_shape),
                                 dtype=np.int32)
        else:
            block = rng.standard_normal(
                (rows, *input_shape)).astype(np.float32)
        payload = json.dumps({"inputs": block.tolist()}).encode()
        route = "/v1/predict"

    def pct(sorted_ms: "list[float]", q: float) -> float:
        return sorted_ms[min(len(sorted_ms) - 1, int(q * len(sorted_ms)))]

    phase_reports = []
    all_lat_ms: "list[float]" = []
    total_errors = 0
    retry_stats = {"retries": 0, "gave_up": 0}
    t0_all = time.perf_counter()
    for pi, (clients, seconds) in enumerate(phases):
        latencies: "list[tuple[float, str | None]]" = []
        errors: "list[str]" = []
        ttfts: "list[float] | None" = [] if ttfts_wanted else None
        lock = threading.Lock()
        stop = threading.Event()
        threads = [threading.Thread(
            target=_client_loop,
            args=(urls[i % len(urls)], payload, stop, latencies, lock,
                  errors, route, ttfts, retry_stats,
                  1000 * pi + i, traces, recorder),
            daemon=True) for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=300)
        wall = time.perf_counter() - t0
        lat_ms = sorted(1e3 * l for l, _ in latencies)
        report = {
            "phase": pi,
            "clients": clients,
            "seconds": seconds,
            "wall_s": round(wall, 2),
            "requests": len(lat_ms),
            "errors": len(errors),
            "requests_per_s": round(len(lat_ms) / wall, 2),
        }
        if lat_ms:
            report["p50_ms"] = round(pct(lat_ms, 0.50), 2)
            report["p95_ms"] = round(pct(lat_ms, 0.95), 2)
            report["p99_ms"] = round(pct(lat_ms, 0.99), 2)
        if ttfts:
            tt = sorted(1e3 * t for t in ttfts)
            report["ttft_p50_ms"] = round(pct(tt, 0.50), 2)
        phase_reports.append(report)
        all_lat_ms.extend(lat_ms)
        total_errors += len(errors)
        print(f"ramp phase {pi}: {clients} clients x {seconds:g}s -> "
              f"{len(lat_ms)} ok, {len(errors)} errors"
              + (f", p50 {report.get('p50_ms')} ms" if lat_ms else ""),
              flush=True)
    wall_all = time.perf_counter() - t0_all
    if not all_lat_ms:
        raise RuntimeError("no ramp request succeeded")
    all_lat_ms.sort()
    return {
        "ramp_phases": phase_reports,
        "rows_per_request": rows,
        "wall_s": round(wall_all, 2),
        "requests": len(all_lat_ms),
        "errors": total_errors,
        "retries_503": retry_stats["retries"],
        "gave_up_503": retry_stats["gave_up"],
        "p50_ms": round(pct(all_lat_ms, 0.50), 2),
        "p95_ms": round(pct(all_lat_ms, 0.95), 2),
        "p99_ms": round(pct(all_lat_ms, 0.99), 2),
    }


def _session_turn(url: str, prompt: "list[int]", sid: str,
                  gen_tokens: int) -> "tuple[float, float, list[int]]":
    """One session turn over the SSE route: returns (ttft_s, latency_s,
    reply_tokens). Streaming is load-bearing here — TTFT is the number
    tiering moves (prefill skipped vs suffix-only vs full re-prefill),
    so the turn must observe first-token time, not just total."""
    import urllib.request

    body = {"prompt_tokens": [prompt], "max_new_tokens": gen_tokens,
            "stream": True, "session": sid}
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": format_traceparent(new_trace_id(),
                                                   new_span_id())})
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=300) as r:
        ttft = None
        last = None
        for line in r:
            if not line.startswith(b"data: "):
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
            last = json.loads(line[6:])
    if last is None or "error" in last or not last.get("done"):
        raise RuntimeError(f"stream ended badly: {last}")
    return ttft, time.perf_counter() - t0, last["tokens"][0]


def _release_session(url: str, sid: str) -> bool:
    import urllib.request

    req = urllib.request.Request(
        url + "/v1/session/release",
        data=json.dumps({"session": sid}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return bool(json.loads(r.read()).get("released"))


def _session_loop(url: str, idx: int, turns: int, rows: int,
                  gen_tokens: int, release: bool, lock, turn1: list,
                  warm: list, errors: list) -> None:
    """One multi-turn chat session: each turn's prompt is the previous
    turn's prompt + reply + two fresh 'user' tokens, so turn g strictly
    extends the chain turn g-1 parked. Per-session prompt seeds differ —
    sessions must NOT share prefixes, or pcache sharing would hand every
    session after the first a warm turn 1."""
    rng = np.random.default_rng(1000 + idx)
    prompt = rng.integers(1, 1000, size=(max(4, rows),)).tolist()
    sid = f"loadgen-{idx}"
    for turn in range(turns):
        try:
            ttft, _lat, reply = _session_turn(url, prompt, sid, gen_tokens)
        except Exception as e:  # noqa: BLE001 — record, session ends
            with lock:
                errors.append(f"session {idx} turn {turn}: {e}")
            return
        with lock:
            (turn1 if turn == 0 else warm).append(ttft)
        prompt = prompt + reply + rng.integers(1, 1000, size=(2,)).tolist()
        if release and turn < turns - 1:
            try:
                _release_session(url, sid)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"session {idx} release {turn}: {e}")
                return


def run_sessions(url: "str | list[str]", *, sessions: int, turns: int,
                 rows: int, gen_tokens: int, release: bool = True) -> dict:
    """Multi-turn session load: N concurrent sessions x K turns each,
    session ids carried across turns (the first client of the session-id
    API). ``release`` parks each chain between turns via
    /v1/session/release — against a --tier-host-mb server the next turn
    swaps it back in (warm TTFT ~ suffix prefill + restore), against a
    tierless one the chain is dropped (warm TTFT ~ full re-prefill):
    the warm/turn-1 TTFT pair IS the tiering measurement.

    With a URL list, session i lives entirely on endpoint
    ``i % len(urls)`` — a session split across endpoints would be a
    cache miss on every turn, which is the router's problem to solve,
    not the client's."""
    urls = [url] if isinstance(url, str) else list(url)
    turn1: "list[float]" = []
    warm: "list[float]" = []
    errors: "list[str]" = []
    lock = threading.Lock()
    threads = [threading.Thread(
        target=_session_loop,
        args=(urls[i % len(urls)], i, turns, rows, gen_tokens, release,
              lock, turn1, warm, errors),
        daemon=True) for i in range(sessions)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall = time.perf_counter() - t0
    if not turn1:
        raise RuntimeError(f"no session finished turn 1; "
                           f"errors: {errors[:3]}")

    def p50(xs: "list[float]") -> float:
        s = sorted(xs)
        return s[len(s) // 2]

    out = {
        "sessions": sessions,
        "turns": turns,
        "rows_per_request": rows,
        "gen_tokens_per_request": gen_tokens,
        "release_between_turns": release,
        "wall_s": round(wall, 2),
        "requests": len(turn1) + len(warm),
        "errors": len(errors),
        "retries_503": 0,
        "gave_up_503": 0,
        "turn1_ttft_p50_ms": round(1e3 * p50(turn1), 2),
    }
    if warm:
        out["warm_ttft_p50_ms"] = round(1e3 * p50(warm), 2)
        out["warm_vs_turn1_ttft"] = round(p50(warm) / max(p50(turn1),
                                                          1e-9), 3)
    return out


def server_histogram_quantiles(metrics_text: str) -> dict:
    """Server-side latency quantiles estimated from a /metrics scrape's
    histograms (k3stpu/obs) — the numbers a Prometheus
    histogram_quantile() over the same scrape would report. Printed next
    to the client-measured percentiles: client >> server means time
    spent OUTSIDE the engine (HTTP, JSON, client queueing); server >>
    client means the estimate's bucket resolution, not a real gap."""
    from k3stpu.obs import (
        parse_prometheus_histograms,
        quantile_from_buckets,
    )

    hists = parse_prometheus_histograms(metrics_text)
    out: dict = {}
    for short, name in (("ttft", "k3stpu_request_ttft_seconds"),
                        ("e2e", "k3stpu_request_e2e_seconds"),
                        ("queue_wait",
                         "k3stpu_request_queue_wait_seconds")):
        h = hists.get(name)
        if not h or not h["count"]:
            continue
        for q in (0.50, 0.95, 0.99):
            v = quantile_from_buckets(h["bounds"], h["cumulative"],
                                      h["count"], q)
            if v is not None:
                out[f"server_{short}_p{int(q * 100)}_ms"] = round(v * 1e3,
                                                                  2)
    return out


def spec_report(metrics_text: str) -> dict:
    """Speculation counters lifted from a /metrics scrape — the A/B
    column ``--report-spec`` prints next to the client percentiles.
    Accepted-tokens/dispatch is the speedup knob: each speculative
    dispatch costs ~one plain decode dispatch, so this number is the
    realized tokens-per-round-trip multiplier (minus the +1 correction
    token a plain dispatch also produces). Empty dict when the server
    has no speculation families (not running --speculate, or an older
    build)."""
    vals: "dict[str, str]" = {}
    for line in metrics_text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        try:
            key, val = line.rsplit(None, 1)
        except ValueError:
            continue
        vals[key] = val
    try:
        accepted = float(vals["k3stpu_serve_spec_accepted_tokens_total"])
        dispatches = float(vals["k3stpu_serve_spec_dispatches_total"])
        ratio = float(vals["k3stpu_serve_spec_accept_ratio"])
    except (KeyError, ValueError):
        return {}
    return {
        "spec_dispatches": int(dispatches),
        "spec_accept_ratio": round(ratio, 4),
        "spec_accepted_tokens_per_dispatch": (
            round(accepted / dispatches, 2) if dispatches else None),
    }


def _print_quantile_skew(result: dict) -> None:
    """Client percentiles next to the server's histogram estimates —
    the at-a-glance skew check (see server_histogram_quantiles)."""
    rows = [("e2e", "{}_ms", "server_e2e_{}_ms"),
            ("ttft", "ttft_{}_ms", "server_ttft_{}_ms")]
    lines = []
    for label, cfmt, sfmt in rows:
        cells = []
        for p in ("p50", "p95", "p99"):
            c, s = result.get(cfmt.format(p)), result.get(sfmt.format(p))
            if c is not None and s is not None:
                cells.append(f"{p} {c} / {s}")
        if cells:
            lines.append(f"  {label:5s} {'   '.join(cells)}")
    if lines:
        print("latency quantiles, client-measured / server-histogram "
              "(ms):", flush=True)
        for ln in lines:
            print(ln, flush=True)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description="inference-server load test")
    ap.add_argument("--url", default=None,
                    help="existing server; default self-hosts one in-process")
    ap.add_argument("--endpoints", default=None, metavar="URL[,URL...]",
                    help="comma-separated live endpoints — N replicas for "
                         "a client-side spread (client i sticks to "
                         "endpoint i %% N), or ONE router URL for the "
                         "routed comparison. Every response's "
                         "X-K3STPU-Replica header attributes the request, "
                         "so the result (and each --json record) gains a "
                         "per-replica p50/p95/p99 breakdown either way. "
                         "Mutually exclusive with --url/self-hosting")
    ap.add_argument("--model", default="transformer",
                    choices=["resnet50", "resnet18-tiny", "transformer",
                             "transformer-medium", "transformer-tiny"])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="examples per request (1 = worst case for an "
                         "uncoalesced server)")
    ap.add_argument("--batch-window-ms", type=float, default=5.0,
                    help="self-hosted server's coalescing window (0 = off)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--generate-tokens", type=int, default=0,
                    help="load /v1/generate instead of /v1/predict: each "
                         "request generates this many tokens (measures the "
                         "decode loop the engine schedules)")
    ap.add_argument("--stream", action="store_true",
                    help="generate load rides the SSE streaming route; "
                         "adds ttft_p50_ms/ttft_p95_ms (time to first "
                         "token) to the result")
    ap.add_argument("--continuous-batching", action="store_true",
                    help="self-hosted server runs the slot-scheduled "
                         "generate engine (the before/after comparison "
                         "for --generate-tokens load)")
    ap.add_argument("--quant", default=None,
                    choices=["int8", "int8-dynamic"],
                    help="self-hosted server serves quantized weights "
                         "(compare against the float run)")
    ap.add_argument("--kv-cache-dtype", default=None, choices=["int8"])
    ap.add_argument("--decode-block", type=int, default=4,
                    help="engine tokens per device dispatch when "
                         "--continuous-batching (see server --decode-block)")
    ap.add_argument("--prompt-cache", type=int, default=0,
                    help="with --continuous-batching: self-hosted server "
                         "caches this many prefilled prompt KV rows. The "
                         "load uses ONE fixed prompt (--rows sets its "
                         "length), so every request after the first is an "
                         "exact hit — the measured delta vs --prompt-cache "
                         "0 is the prefill-skip win")
    ap.add_argument("--kv-page-size", type=int, default=None,
                    help="with --continuous-batching: paged KV cache with "
                         "this page size (see server --kv-page-size); the "
                         "engine stats in LOADGEN_JSON then carry the "
                         "page-pool gauges")
    ap.add_argument("--kv-pages", type=int, default=None,
                    help="pool size for --kv-page-size (default: full "
                         "dense capacity)")
    ap.add_argument("--speculate", action="store_true",
                    help="self-hosted server decodes speculatively "
                         "(n-gram drafter inside the engine; requires "
                         "--continuous-batching and --kv-page-size)")
    ap.add_argument("--spec-gamma", type=int, default=4,
                    help="max draft tokens per slot per speculative "
                         "dispatch (with --speculate)")
    ap.add_argument("--mix", default=None, metavar="SPEC",
                    help="mixed short/long generate traffic: "
                         "'short:long=<w>:<w>' (e.g. short:long=9:1) "
                         "splits the client pool by weight — short "
                         "prompts are --rows tokens, long prompts "
                         "--long-prompt-tokens. Rides the SSE route and "
                         "reports per-class TTFT and TPOT p50/p95/p99 "
                         "(the disagg comparison's workload, "
                         "docs/DISAGG.md). Requires --generate-tokens")
    ap.add_argument("--long-prompt-tokens", type=int, default=2048,
                    help="long-class prompt length for --mix (the "
                         "prefill-interference source; raise --seq-len "
                         "to fit it plus --generate-tokens)")
    ap.add_argument("--ramp", default=None, metavar="SPEC",
                    help="piecewise load schedule instead of a flat "
                         "--seconds window: comma-separated "
                         "'<mult>x:<seconds>s' phases, multipliers of "
                         "--clients (e.g. '1x:30s,4x:60s,1x:30s' = base, "
                         "4x surge, base). The result (and --json) gains "
                         "per-phase p50/p95/p99 — the surge shape "
                         "autoscaler runs are judged by")
    ap.add_argument("--sessions", type=int, default=0,
                    help="multi-turn session mode: run this many "
                         "concurrent sessions instead of the open-loop "
                         "client load. Each session runs --turns "
                         "/v1/generate turns under one session id, each "
                         "turn's prompt extending the last turn's "
                         "prompt+reply; reports warm-turn TTFT vs "
                         "turn-1 TTFT (requires --generate-tokens; "
                         "self-hosted servers need --continuous-"
                         "batching --kv-page-size)")
    ap.add_argument("--turns", type=int, default=4,
                    help="turns per session with --sessions")
    ap.add_argument("--no-session-release", action="store_true",
                    help="with --sessions: keep chains pinned in the "
                         "prompt cache between turns instead of "
                         "releasing them (the all-HBM upper bound; "
                         "default releases, so warm turns measure the "
                         "tier restore — or the full re-prefill on a "
                         "tierless server)")
    ap.add_argument("--tier-host-mb", type=int, default=None,
                    help="self-hosted server parks released session "
                         "chains in a host-RAM tier of this many MiB "
                         "(see server --tier-host-mb)")
    ap.add_argument("--tier-dir", default=None,
                    help="self-hosted server's disk spill directory "
                         "for the tier (see server --tier-dir)")
    ap.add_argument("--tier-watermark", type=int, default=0,
                    help="self-hosted server's free-page low watermark "
                         "for tier demotion (see server "
                         "--tier-watermark)")
    ap.add_argument("--report-spec", action="store_true",
                    help="after the run, scrape the speculation counters "
                         "from /metrics and print accepted-tokens/"
                         "dispatch + accept ratio next to the client "
                         "p50/p95/p99 (pairs with a --speculate server)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full result plus a per-request "
                         "rid<->trace-id table (failures marked) to this "
                         "file; a failed request's trace_id can be looked "
                         "up directly in the server's /debug/trace")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write the CLIENT-side Chrome trace (one tid per "
                         "request, wall-anchored) to this file; merge with "
                         "the server's /debug/trace via tools/trace_merge.py")
    ap.add_argument("--record-arrivals", default=None, metavar="PATH",
                    help="dump the per-request arrival-time/class/"
                         "prompt-shape trace (k3stpu-sim-trace-v1) to "
                         "this file, replayable through the fleet "
                         "simulator: python -m k3stpu.sim --trace PATH")
    args = ap.parse_args(argv)
    urls: "list[str] | None" = None
    if args.endpoints:
        if args.url:
            ap.error("--endpoints and --url are mutually exclusive "
                     "(one router URL goes in --endpoints)")
        urls = [u.strip().rstrip("/")
                for u in args.endpoints.split(",") if u.strip()]
        if not urls:
            ap.error("--endpoints needs at least one URL")
    if args.stream and args.generate_tokens <= 0:
        ap.error("--stream requires --generate-tokens (the SSE route is "
                 "generation-only)")
    ramp_phases = None
    if args.ramp:
        if args.sessions:
            ap.error("--ramp and --sessions are mutually exclusive")
        try:
            ramp_phases = parse_ramp(args.ramp, args.clients)
        except ValueError as e:
            ap.error(str(e))
    mix = None
    if args.mix:
        if args.ramp or args.sessions:
            ap.error("--mix is mutually exclusive with --ramp/--sessions")
        if args.generate_tokens <= 1:
            ap.error("--mix requires --generate-tokens >= 2 (TPOT is "
                     "defined past the first token)")
        try:
            mix = parse_mix(args.mix)
        except ValueError as e:
            ap.error(str(e))
    if args.sessions:
        if args.record_arrivals:
            ap.error("--record-arrivals covers the shared client loop "
                     "(load/mix/ramp); the session loop drives turns "
                     "from completions, which the sim's session "
                     "generator models directly")
        if args.generate_tokens <= 0:
            ap.error("--sessions requires --generate-tokens (sessions "
                     "are a generate workload)")
        if args.url is None and urls is None \
                and not (args.continuous_batching and args.kv_page_size):
            ap.error("--sessions self-hosting needs --continuous-"
                     "batching and --kv-page-size (session ids name "
                     "paged chains)")

    url = args.url or (urls[0] if urls else None)
    card_url = None
    if url is None:
        from http.server import ThreadingHTTPServer

        from k3stpu.serve.server import (
            BATCH_SIZES,
            InferenceServer,
            make_app,
            served_batch,
            start_telemetry_thread,
        )

        server = InferenceServer(
            model_name=args.model, image_size=args.image_size,
            seq_len=args.seq_len, batch_window_ms=args.batch_window_ms,
            continuous_batching=args.continuous_batching,
            decode_block=args.decode_block,
            prompt_cache=args.prompt_cache,
            kv_page_size=args.kv_page_size, kv_pages=args.kv_pages,
            speculate=args.speculate, spec_gamma=args.spec_gamma,
            quant=args.quant, kv_cache_dtype=args.kv_cache_dtype,
            tier_host_mb=args.tier_host_mb, tier_dir=args.tier_dir,
            tier_watermark=args.tier_watermark,
            shard_devices=None)  # None = all local devices; the engine
        # runs tensor-parallel now (mesh-sharded KV cache), so the old
        # single-device pin would just hide the pod's other chips.
        if args.sessions:
            # Session warmup: ONE throwaway session walks all K turn
            # widths, so every pow2 prefill bucket the measured sessions
            # will hit — and, with a tier, the swap-out/swap-in programs
            # — compiles before the measured turns.
            print("warming up (session path)...", flush=True)
            rng = np.random.default_rng(0)
            p = _gen_prompt(args.rows)
            for turn in range(args.turns):
                reply = server.generate_tokens(
                    [p], max_new_tokens=args.generate_tokens,
                    session="__warmup__")[0]
                p = p + reply + rng.integers(1, 1000, size=(2,)).tolist()
                if not args.no_session_release and turn < args.turns - 1:
                    server.release_session("__warmup__")
            server.release_session("__warmup__")
            server.reset_stats()
        elif args.generate_tokens > 0:
            # Compile prefill+decode (and engine programs) BEFORE the
            # measured window — first-request JIT would otherwise land in
            # the committed before/after numbers. Width-matched: the
            # warmup prompt pads to the SAME pow2 bucket as the load's
            # (--rows-long) prompt, so the real prefill program is the
            # one compiled here, not mid-measurement.
            print("warming up (generate path)...", flush=True)
            server.generate_tokens([_gen_prompt(args.rows)],
                                   max_new_tokens=2)
            if mix is not None:
                # Mixed load dispatches BOTH width buckets; the long
                # class's prefill program must compile here too.
                server.generate_tokens(
                    [_gen_prompt(args.long_prompt_tokens)],
                    max_new_tokens=2)
            # Warmup dispatches are compile-dominated: without the reset
            # they poison the committed device tokens/s (same reason
            # server.warmup() resets for the predict path).
            server.reset_stats()
        else:
            print("warming up...", flush=True)
            # Warm only the batch sizes this load can dispatch (largest
            # coalesced batch = clients * rows, padded by the server's own
            # served_batch policy): each warmup is a full JIT round-trip
            # through the device tunnel, and compiling the 32-wide forward
            # for an 8-client run is pure exposure to tunnel flakes.
            target = min(args.clients * args.rows, BATCH_SIZES[-1])
            needed = [b for b in BATCH_SIZES if b < target]
            needed.append(served_batch(target))
            server.warmup(tuple(needed))
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_app(server))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        # Short interval: a 15-20 s load window must produce fresh drops
        # so a tpu-info run right after shows live MEMORY/UTIL, not "n/a"
        # (the host tool treats drops older than 120 s as stale).
        start_telemetry_thread(server, interval=2.0)
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
    card_url = url + "/v1/models"

    import urllib.request

    with urllib.request.urlopen(card_url, timeout=60) as r:
        card = json.loads(r.read())

    traces = ClientTraces()
    recorder = ArrivalRecorder() if args.record_arrivals else None
    if args.sessions:
        result = run_sessions(
            urls or url, sessions=args.sessions, turns=args.turns,
            rows=args.rows, gen_tokens=args.generate_tokens,
            release=not args.no_session_release)
    elif mix is not None:
        result = run_mixed(
            urls or url, clients=args.clients, seconds=args.seconds,
            mix=mix, rows=args.rows, long_rows=args.long_prompt_tokens,
            generate_tokens=args.generate_tokens, traces=traces,
            recorder=recorder)
    elif ramp_phases is not None:
        result = run_ramp(
            urls or url, phases=ramp_phases, rows=args.rows,
            input_shape=tuple(card["input_shape"]),
            input_dtype=card["input_dtype"],
            generate_tokens=args.generate_tokens, stream=args.stream,
            traces=traces, recorder=recorder)
    else:
        result = run_load(
            urls or url, clients=args.clients, seconds=args.seconds,
            rows=args.rows, input_shape=tuple(card["input_shape"]),
            input_dtype=card["input_dtype"],
            generate_tokens=args.generate_tokens, stream=args.stream,
            traces=traces, recorder=recorder)

    # Server-side histogram quantiles from the same run (best-effort:
    # an older server without the obs layer just yields none).
    metrics_text = None
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=60) as r:
            metrics_text = r.read().decode()
        result.update(server_histogram_quantiles(metrics_text))
    except Exception as e:  # noqa: BLE001 — the load numbers still stand
        print(f"(/metrics scrape failed: {e})", flush=True)
    if args.report_spec:
        spec = spec_report(metrics_text) if metrics_text else {}
        if spec:
            result.update(spec)
        else:
            print("(--report-spec: no speculation families in the "
                  "/metrics scrape)", flush=True)

    with urllib.request.urlopen(card_url, timeout=60) as r:
        card = json.loads(r.read())
    result.update({
        "model": card["model"],
        "window_ms": card["batching"]["window_ms"],
        "avg_examples_per_dispatch":
            card["throughput"]["avg_examples_per_dispatch"],
        "device_examples_per_s": card["throughput"]["examples_per_s"],
        "device_tokens_per_s": card["throughput"]["tokens_per_s"],
        "engine": card.get("engine"),
        "devices": card["devices"][:1],
    })
    if args.json:
        records = traces.records()
        with open(args.json, "w") as f:
            json.dump({"summary": result, "requests": records}, f,
                      indent=1)
        failed = sum(1 for r in records if not r["ok"])
        print(f"wrote {args.json}: {len(records)} requests "
              f"({failed} failed)", flush=True)
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            json.dump(traces.chrome_trace(), f)
        print(f"wrote client trace {args.trace_out}", flush=True)
    if recorder is not None:
        n = recorder.dump(args.record_arrivals)
        print(f"wrote arrival trace {args.record_arrivals}: {n} requests "
              f"({ArrivalRecorder.SCHEMA})", flush=True)
    _print_quantile_skew(result)
    if result.get("per_replica"):
        print("per-replica latency (ms):", flush=True)
        for rep, st in result["per_replica"].items():
            print(f"  {rep}: {st['requests']} reqs  "
                  f"p50 {st['p50_ms']}  p95 {st['p95_ms']}  "
                  f"p99 {st['p99_ms']}", flush=True)
    if result.get("spec_accepted_tokens_per_dispatch") is not None:
        print(f"spec: {result['spec_accepted_tokens_per_dispatch']} "
              f"accepted-tokens/dispatch over "
              f"{result['spec_dispatches']} verify dispatches "
              f"(accept ratio {result['spec_accept_ratio']})",
              flush=True)
    if result.get("classes"):
        print("per-class latency (ms):", flush=True)
        for tag, st in result["classes"].items():
            if st.get("ttft_p50_ms") is None:
                print(f"  {tag:5s} ({st['prompt_tokens']} prompt toks): "
                      f"{st['requests']} reqs, no successes", flush=True)
                continue
            print(f"  {tag:5s} ({st['prompt_tokens']} prompt toks): "
                  f"{st['requests']} reqs  "
                  f"ttft p50 {st['ttft_p50_ms']} p99 {st['ttft_p99_ms']}  "
                  f"tpot p50 {st['tpot_p50_ms']} p99 {st['tpot_p99_ms']}",
                  flush=True)
    if result.get("warm_ttft_p50_ms") is not None:
        print(f"sessions: turn-1 TTFT p50 {result['turn1_ttft_p50_ms']} "
              f"ms, warm-turn TTFT p50 {result['warm_ttft_p50_ms']} ms "
              f"(warm/turn1 {result['warm_vs_turn1_ttft']})", flush=True)
    if result["retries_503"] or result["gave_up_503"]:
        print(f"503 backoff: {result['retries_503']} retried, "
              f"{result['gave_up_503']} gave up "
              f"(cap {_MAX_RETRIES_503} retries/request)", flush=True)
    print("LOADGEN_JSON " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
