"""Speculative decoding: draft-proposed, target-verified generation.

Decode on a TPU is HBM-bound — each token streams every weight once for
one matmul row. Speculative decoding converts that into MXU work the chip
has to spare: a small DRAFT model proposes ``gamma`` tokens with cheap
decode steps, then the TARGET verifies all of them in ONE ``extend``
forward (models/transformer.py) whose chunk matmuls batch over the
proposals. With greedy verification the output is EXACTLY the target's
own greedy continuation — the draft affects only how many steps it takes,
never what comes out (tested against ``generate()`` token for token, with
a deliberately unrelated draft model).

Rollback rides the per-row cache index: rejected proposals are "undone"
by moving the row's index back — slots beyond it are invisible to the
pos <= index mask and the next append overwrites them. No copies, no
paged bookkeeping.

Per-row acceptance: each batch row keeps its own matched-prefix length
every round, so ragged batches verify independently inside the shared
static-shape programs.

Two drafting strategies share the verify math:

- ``speculative_generate`` below: a small draft MODEL proposes (the
  standalone two-model form, one whole batch per call).
- ``NgramDrafter``: model-free prompt-lookup drafting for the engine's
  slot-scheduled loop (serve/engine.py ``speculate=True``) — proposals
  come from matching the sequence's own recent suffix against its
  earlier occurrences, so repetitive continuations (code, templated
  text, degenerate greedy tails) verify several tokens per dispatch
  with zero extra model weights. A wrong proposal costs nothing but
  verify width: greedy verification keeps output exact regardless.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import set_cache_index
from k3stpu.serve.programs import decode_core, extend_core, prefill_core

# Shared cores (serve/programs.py) + the verifier's in-jit argmax epilogue
# (shipping (B, G, V) logits to the host every round would swamp the win).


@functools.partial(jax.jit, static_argnums=(0,))
def _prefill(model, params, block, lens):
    cache, last = prefill_core(model, params, block, lens)
    return cache, jnp.argmax(last, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0,))
def _decode_argmax(model, params, cache, toks):
    cache, logits = decode_core(model, params, cache, toks)
    return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0,))
def _extend_argmax(model, params, cache, chunk):
    """Verify chunk (B, G): returns per-position greedy next tokens
    (B, G) — g[:, j] is the target's next token after chunk[:, :j+1]."""
    cache, logits = extend_core(model, params, cache, chunk)
    return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)


class NgramDrafter:
    """Prompt-lookup proposal source: continue the most recent earlier
    occurrence of the sequence's current suffix.

    For each n in ``max_ngram..min_ngram`` (longest suffix first — a
    longer match is stronger evidence), scan backwards for the latest
    earlier position where the last n tokens also occur, preferring a
    match with a full ``depth`` tokens of continuation (a run of one
    repeated token matches everywhere near the end, but only an earlier
    occurrence has room to propose the whole depth). No match at any n
    returns [] — the engine then runs its plain decode path for the
    dispatch, so non-repetitive traffic never pays verify width for
    doomed proposals.

    Pure host-side and deterministic: same history, same proposals —
    which keeps the engine's speculative output reproducible run to
    run. ``window`` bounds the backward scan so per-dispatch drafting
    stays O(window * max_ngram) however long the sequence grows.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 2,
                 window: int = 256):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}..{max_ngram}")
        if window < max_ngram + 1:
            raise ValueError(f"window {window} too small for "
                             f"max_ngram {max_ngram}")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.window = window

    def propose(self, history: "list[int]", depth: int) -> "list[int]":
        """Up to ``depth`` proposed continuation tokens of ``history``
        (prompt + everything generated so far), or [] when no suffix
        recurs."""
        if depth <= 0:
            return []
        h = history[-self.window:]
        n_h = len(h)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if n_h < n + 1:
                continue
            suffix = h[-n:]
            partial = None
            # Latest occurrence first; the first hit with a full-depth
            # continuation wins, else the latest partial one.
            for i in range(n_h - n - 1, -1, -1):
                if h[i:i + n] != suffix:
                    continue
                cont = h[i + n:i + n + depth]
                if len(cont) == depth:
                    return list(cont)
                if partial is None and cont:
                    partial = list(cont)
            if partial is not None:
                return partial
        return []


def speculative_generate(
    target_model, target_params, draft_model, draft_params,
    prompt: np.ndarray, prompt_lens: np.ndarray, max_new_tokens: int,
    *, gamma: int = 4,
) -> "tuple[np.ndarray, dict]":
    """Greedy speculative generation for a padded (B, P) prompt block.

    Returns ``(tokens (B, max_new_tokens) int32, stats)`` where tokens are
    EXACTLY the target model's greedy continuation per row. ``stats``
    reports rounds, mean accepted proposals per round, and the proposal
    acceptance rate (the speedup knob: wall clock ~ rounds x (gamma draft
    steps + 1 target extend) instead of max_new_tokens target steps).
    """
    b, p = prompt.shape
    for model, name in ((target_model, "target"), (draft_model, "draft")):
        cfg = getattr(model.config, "base", model.config)
        if p + max_new_tokens + gamma + 1 > cfg.max_seq_len:
            raise ValueError(
                f"prompt {p} + budget {max_new_tokens} + gamma+1 "
                f"{gamma + 1} exceeds the {name} cache "
                f"({cfg.max_seq_len})")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")

    block = jnp.asarray(prompt, jnp.int32)
    lens = jnp.asarray(prompt_lens, jnp.int32)
    t_cache, x0 = _prefill(target_model, target_params, block, lens)
    d_cache, _ = _prefill(draft_model, draft_params, block, lens)
    # Both caches hold the prompt K/V; x0 (the first emitted token) is the
    # target's greedy pick at each row's last real position.
    base_idx = np.asarray(lens)               # tokens strictly before x0
    emitted = [[int(t)] for t in np.asarray(x0)]
    rounds = 0
    accepted_total = 0
    proposed_total = 0

    need = lambda: any(len(e) < max_new_tokens for e in emitted)
    while need():
        rounds += 1
        # Draft proposes gamma tokens. One EXTRA step consumes d_gamma so
        # the draft cache holds K/V for x0..d_gamma — required when full
        # acceptance carries the bonus token and the next round's draft
        # starts right after d_gamma. (Its proposal is discarded; the
        # draft is the cheap model, the extra step is noise.)
        cur = x0
        props = []
        for _ in range(gamma + 1):
            d_cache, cur = _decode_argmax(draft_model, draft_params,
                                          d_cache, cur)
            props.append(cur)
        props_arr = jnp.stack(props[:gamma], axis=1)  # (b, gamma)
        # gamma+1-wide verify chunk [x0, d1..d_gamma]: position j scores
        # the next token after chunk[:, :j+1], so g[:, :gamma] judges the
        # proposals AND g[:, gamma] is a free bonus token when everything
        # matches — the standard gamma+1 tokens per fully-accepted round.
        chunk = jnp.concatenate([x0[:, None], props_arr], axis=1)
        t_cache, g = _extend_argmax(target_model, target_params, t_cache,
                                    chunk)            # (b, gamma+1)

        eq = np.asarray(props_arr == g[:, :gamma])    # (b, gamma)
        # m_r = longest all-matched prefix of this row's proposals.
        m = np.cumprod(eq, axis=1).sum(axis=1)        # (b,)
        props_np, g_np = np.asarray(props_arr), np.asarray(g)
        new_x0 = np.asarray(x0, np.int32).copy()
        consumed = np.zeros((b,), np.int64)
        n_live = 0
        for r in range(b):
            if len(emitted[r]) >= max_new_tokens:
                # Frozen row: it rode the batch's static-shape draft/verify
                # but must not advance — its index would otherwise creep
                # ~gamma+1 per round past the p+budget+gamma+1 bound the
                # entry check enforced, and its dead work would inflate
                # the acceptance stats.
                continue
            n_live += 1
            mr = int(m[r])
            # Emit the matched proposals plus the target's token at the
            # first divergence — which on full acceptance IS the bonus.
            take = props_np[r, :mr].tolist() + [int(g_np[r, mr])]
            emitted[r].extend(take)
            new_x0[r] = take[-1]
            # Cache rows hold everything strictly before new_x0:
            # x0 + the mr accepted proposals.
            consumed[r] = mr + 1
            accepted_total += mr
        proposed_total += n_live * gamma
        base_idx = base_idx + consumed
        new_idx = jnp.asarray(base_idx, jnp.int32)
        # Per-row rollback (free: slots past the index are invisible).
        t_cache = set_cache_index(t_cache, new_idx)
        d_cache = set_cache_index(d_cache, new_idx)
        x0 = jnp.asarray(new_x0)

    out = np.stack([np.asarray(e[:max_new_tokens], np.int32)
                    for e in emitted])
    stats = {
        "rounds": rounds,
        "gamma": gamma,
        "proposed": proposed_total,
        "accepted": accepted_total,
        "acceptance_rate": (round(accepted_total / proposed_total, 4)
                            if proposed_total else None),
        "tokens_per_round": (round(sum(len(e) for e in emitted) / b / rounds,
                                   2) if rounds else None),
    }
    return out, stats
