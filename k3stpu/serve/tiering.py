"""Host-memory page tier behind the paged-KV allocator.

The paged engine (PR 1) keeps every cached page chain resident in the
device pool: an idle chat session either pins HBM through the prompt
cache's ``_pinned`` refcounts or loses its KV entirely and pays a full
re-prefill on the next turn. ``HostPageStore`` is the middle ground —
a byte-capped, last-use-ordered store of *gathered* page chains
(``jax.device_get`` of each ``*_pages`` leaf at the chain's indices,
so one contiguous host ndarray per leaf) that the engine consults
before declaring a prompt-cache miss. Swap-in is one batched
``device_put`` + scatter into freshly allocated pages
(``GenerateEngine._restore_pages``); everything else about the entry —
key scheme, prefix-match rule, pin/refcount discipline — is the prompt
cache's, so bit-exactness of a restored chain reduces to the already
pinned pcache-hit invariants (docs/TIERING.md has the full argument).

Design points:

- **Keys** are the prompt cache's ``(adapter, prompt_tuple)`` — the
  tier is a backing store *behind* the pcache, not a second cache with
  its own identity. ``match()`` implements the same longest-prefix rule
  as ``GenerateEngine._pcache_lookup`` so a tier probe and a pcache
  probe can be compared directly.
- **Eviction** is last-use order (insertion-ordered dict, refreshed on
  hit), capped by ``capacity_bytes``. With ``spill_dir`` set, evictees
  spill to disk instead of vanishing — the third tier. Spilled files
  are written tmp-then-``os.replace`` (atomic on POSIX) and carry a
  crc32 of the payload; a torn or bit-rotted spill fails the checksum
  at load and surfaces as ``TierCorrupt``, which the engine's swap-in
  path degrades to a cold prefill (chaos point ``tier_swap`` drills
  exactly this).
- **The spill format is a handoff format.** Filenames are store-unique
  and namespaced by intent: capacity evictions write private
  ``tier-<pid>-<store>-<seq>.kv`` files no peer will touch, while the
  explicit park path (``spill(key)`` — the autoscaler's drain) writes
  ``park-…`` files that are offered for adoption. Payloads are
  self-describing (the key is in the pickle, checked at load), and
  ``match()`` adopts unclaimed ``park-*`` files it finds in
  ``spill_dir`` — so a replica sharing a spill directory inherits the
  chains a scaled-away victim parked (docs/AUTOSCALING.md). One owner
  at a time is enforced, not hoped for: an adopter CLAIMS a park file
  by atomically renaming it into its own private namespace, so two
  surviving stores racing for the same orphan resolve at the rename
  (the loser's rename fails and it walks away) instead of both
  indexing it and one finding the file gone at load time. The
  per-probe cost is one ``os.stat`` of the directory — the full scan
  runs only when the directory mtime says something changed.
- **No device handles.** Values are plain numpy arrays + ints; the
  store survives ``_crash_reset`` rebuilding the device pool, which is
  what makes it a *recovery* tier and not just a cache annex.

Thread-safety: all mutation happens on the engine loop thread (HTTP
threads marshal session-release through the engine queue), so the
store itself takes no locks; ``stats()`` reads two ints and is safe to
call from anywhere.
"""

from __future__ import annotations

import itertools
import os
import pickle
import time
import zlib
from typing import Any

# Per-process store counter: spill filenames carry (pid, store-id) so
# two stores NEVER collide — across processes (distinct pids) or within
# one (distinct store ids; bench and tests run multiple in-process
# servers against one shared spill_dir).
_STORE_IDS = itertools.count(1)

Key = tuple[Any, tuple]  # (adapter, prompt_tuple) — the pcache key scheme


class TierCorrupt(RuntimeError):
    """A spilled entry failed its checksum (torn write, bit rot)."""


def encode_entry(key: Key, length: int, pages: "dict[str, Any]",
                 last: Any = None) -> bytes:
    """THE wire format for a gathered page chain: 4-byte big-endian
    crc32 of the pickled ``(key, length, pages, last)`` payload, then
    the payload. One format for every mover of a chain — disk spills,
    drain park files, and the disagg prefill→decode KV stream
    (docs/DISAGG.md) — so all of them share the same torn-transfer
    detection and the same ``decode_entry`` round-trip."""
    payload = pickle.dumps((key, length, pages, last),
                           protocol=pickle.HIGHEST_PROTOCOL)
    return zlib.crc32(payload).to_bytes(4, "big") + payload


def decode_entry(data: bytes) -> "tuple[Key, int, dict[str, Any], Any]":
    """Inverse of ``encode_entry``: ``(key, length, pages, last)``.
    Raises TierCorrupt on a truncated or checksum-failed buffer (torn
    spill file, torn HTTP transfer) — never returns partial data."""
    if len(data) < 4:
        raise TierCorrupt("tier payload truncated")
    crc, payload = int.from_bytes(data[:4], "big"), data[4:]
    if zlib.crc32(payload) != crc:
        raise TierCorrupt("tier payload checksum mismatch")
    return pickle.loads(payload)


class _Entry:
    """One gathered page chain, resident in host RAM or spilled.

    ``pages`` maps "/"-joined cache-leaf path names (e.g.
    ``"0/attn/key_pages"``) to numpy arrays of shape ``(n_pages, ...)``
    — the leaf gathered at the chain's page indices, in chain order.
    ``last`` is the pcache entry's last-position logits (host-side), or
    None for session tails whose next-token distribution is recomputed
    on restore. When spilled, ``pages``/``last`` are None and ``path``
    points at the checksummed pickle on disk.
    """

    __slots__ = ("length", "n_pages", "nbytes", "pages", "last",
                 "session", "path")

    def __init__(self, length: int, n_pages: int, nbytes: int,
                 pages: dict[str, Any] | None, last: Any,
                 session: str | None):
        self.length = length
        self.n_pages = n_pages
        self.nbytes = nbytes
        self.pages = pages
        self.last = last
        self.session = session
        self.path = None  # set when spilled


class HostPageStore:
    """Byte-capped host store of gathered KV page chains.

    capacity_bytes: resident host-RAM budget. Entries past it are
        evicted last-use-first — to ``spill_dir`` when set, to nowhere
        otherwise (the entry is simply dropped, pre-tier behavior).
    spill_dir: optional directory for the disk tier. Created on first
        spill; files are atomic-renamed and checksummed.
    """

    def __init__(self, capacity_bytes: int, spill_dir: str | None = None):
        if capacity_bytes <= 0:
            raise ValueError("tier capacity_bytes must be positive")
        self.capacity = int(capacity_bytes)
        self.spill_dir = spill_dir
        self._entries: dict[Key, _Entry] = {}  # insertion order = LRU
        self._bytes = 0        # resident (non-spilled) host bytes
        self._spill_seq = 0
        self._tag = f"{os.getpid()}-{next(_STORE_IDS)}"
        self._spilled_bytes = 0
        # Every spill path this store has written OR examined: adoption
        # parses each foreign file at most once (corrupt ones included —
        # a bad file must not be re-read on every probe).
        self._known_paths: set[str] = set()
        # spill_dir mtime at the last adoption scan: the probe-path
        # gate that keeps listdir off the request hot path.
        self._adopt_mtime_ns: int | None = None

    # -- write path ----------------------------------------------------

    def put(self, key: Key, length: int, pages: dict[str, Any],
            last: Any = None, session: str | None = None) -> None:
        """Insert (or replace) a gathered chain; evict past capacity."""
        n_pages = 0
        nbytes = 0
        for arr in pages.values():
            n_pages = max(n_pages, int(arr.shape[0]))
            nbytes += int(arr.nbytes)
        if last is not None:
            nbytes += sum(int(x.nbytes) for x in last
                          if hasattr(x, "nbytes"))
        old = self._entries.pop(key, None)
        if old is not None:
            self._forget(old)
        ent = _Entry(length, n_pages, nbytes, pages, last, session)
        self._entries[key] = ent
        self._bytes += nbytes
        while self._bytes > self.capacity and len(self._entries) > 1:
            self._evict_oldest_resident()

    def _evict_oldest_resident(self) -> None:
        for key, ent in self._entries.items():
            if ent.pages is not None:
                break
        else:
            return
        if self.spill_dir is not None:
            self._spill(key, ent)
        else:
            del self._entries[key]
            self._bytes -= ent.nbytes

    def spill(self, key: Key) -> bool:
        """Force ``key``'s entry to the disk tier NOW, in the adoptable
        ``park-*`` namespace — the drain path: a parked chain must
        outlive this process for a surviving replica to adopt it from
        the shared ``spill_dir``. An entry already on disk as a private
        eviction spill is promoted (renamed) into the park namespace.
        True when the entry is parked on disk afterwards; False when
        absent or no ``spill_dir`` is configured."""
        if self.spill_dir is None:
            return False
        ent = self._entries.get(key)
        if ent is None:
            return False
        if ent.pages is not None:
            self._spill(key, ent, park=True)
            return True
        if ent.path is None:
            return False
        if os.path.basename(ent.path).startswith("park-"):
            return True  # already parked
        self._spill_seq += 1
        parked = os.path.join(
            self.spill_dir, f"park-{self._tag}-{self._spill_seq}.kv")
        try:
            os.rename(ent.path, parked)
        except OSError:
            return False
        self._known_paths.add(parked)
        ent.path = parked
        return True

    def _spill(self, key: Key, ent: _Entry, park: bool = False) -> None:
        """Move one resident entry to disk (atomic, checksummed).
        Filenames carry (pid, store-id) so stores sharing a spill_dir
        never collide, and the prefix carries intent: ``tier-`` files
        are this store's private evictions, ``park-`` files are drain
        handoffs offered to peers via ``adopt_orphans``."""
        os.makedirs(self.spill_dir, exist_ok=True)
        self._spill_seq += 1
        path = os.path.join(
            self.spill_dir,
            f"{'park' if park else 'tier'}-{self._tag}"
            f"-{self._spill_seq}.kv")
        self._known_paths.add(path)
        data = encode_entry(key, ent.length, ent.pages, ent.last)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._bytes -= ent.nbytes
        self._spilled_bytes += ent.nbytes
        ent.pages = None
        ent.last = None
        ent.path = path

    # -- read path -----------------------------------------------------

    def match(self, adapter: Any, prompt: tuple) -> Key | None:
        """Longest stored key that is a prefix of ``prompt`` (same rule
        as ``_pcache_lookup``). Does not refresh LRU order — only a
        successful ``load`` counts as use. With a spill_dir the probe
        first adopts any orphaned peer spills so a chain parked by a
        drained replica is matchable here."""
        if self.spill_dir is not None:
            self._maybe_adopt()
        best = None
        for key in self._entries:
            aid, ptuple = key
            if (aid == adapter and len(ptuple) <= len(prompt)
                    and prompt[:len(ptuple)] == ptuple
                    and (best is None or len(ptuple) > len(best[1]))):
                best = key
        return best

    def _maybe_adopt(self) -> None:
        """Probe-path gate for adoption: one ``os.stat`` of the spill
        directory, with the listdir + per-file parse scan only when its
        mtime moved since the last scan (any park, claim, or unlink by
        any store touches the directory)."""
        try:
            mtime = os.stat(self.spill_dir).st_mtime_ns
        except OSError:
            return  # dir not created yet: nothing parked anywhere
        if mtime == self._adopt_mtime_ns:
            return
        # Filesystem timestamps move on coarse clock ticks: a file
        # parked in the same tick AFTER our scan would not move the
        # mtime again. Only cache (and thereafter skip on) an mtime
        # comfortably in the past; a just-modified directory keeps
        # scanning until it quiesces.
        if time.time_ns() - mtime > 50_000_000:  # 50 ms
            self._adopt_mtime_ns = mtime
        else:
            self._adopt_mtime_ns = None
        self.adopt_orphans()

    def adopt_orphans(self) -> int:
        """Index parked spill files (``park-*.kv``) this store did not
        write — chains a peer replica (sharing ``spill_dir``) parked
        before it was scaled away. Each candidate is read once and
        checksum- and shape-verified, then CLAIMED by atomically
        renaming it into this store's private ``tier-`` namespace and
        registered as a spilled entry under its embedded key — stores
        racing for the same orphan resolve at the rename (the loser's
        rename fails and it walks away), never at a later load.
        Corrupt or half-written files are skipped and remembered so
        they are never re-parsed. A key already present locally wins
        over its on-disk twin (the local copy is the one LRU order
        knows about). Returns the number adopted."""
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return 0
        adopted = 0
        for name in sorted(names):
            if not (name.startswith("park-") and name.endswith(".kv")):
                continue
            path = os.path.join(self.spill_dir, name)
            if path in self._known_paths:
                continue
            self._known_paths.add(path)
            try:
                with open(path, "rb") as f:
                    raw = f.read()
                key, length, pages, last = decode_entry(raw)
            except Exception:  # noqa: BLE001 — foreign bytes; skip them
                continue
            if not isinstance(pages, dict) or key in self._entries:
                continue
            self._spill_seq += 1
            claimed = os.path.join(
                self.spill_dir,
                f"tier-{self._tag}-{self._spill_seq}.kv")
            try:
                os.rename(path, claimed)
            except OSError:
                continue  # a peer claimed it between listdir and here
            self._known_paths.add(claimed)
            n_pages = 0
            nbytes = 0
            for arr in pages.values():
                n_pages = max(n_pages, int(arr.shape[0]))
                nbytes += int(arr.nbytes)
            if last is not None:
                nbytes += sum(int(x.nbytes) for x in last
                              if hasattr(x, "nbytes"))
            ent = _Entry(int(length), n_pages, nbytes, None, None, None)
            ent.path = claimed
            self._entries[key] = ent
            self._spilled_bytes += nbytes
            adopted += 1
        return adopted

    def contains(self, key: Key) -> bool:
        return key in self._entries

    def load(self, key: Key) -> tuple[int, dict[str, Any], Any]:
        """Return (length, pages, last) for ``key``, reading the disk
        tier if the entry was spilled. Refreshes last-use order. Raises
        KeyError if absent, TierCorrupt on checksum failure (the caller
        degrades to cold prefill and should ``discard``)."""
        ent = self._entries.pop(key)
        self._entries[key] = ent  # MRU refresh
        if ent.pages is not None:
            return ent.length, ent.pages, ent.last
        try:
            with open(ent.path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise TierCorrupt(f"tier spill unreadable: {e}") from e
        skey, length, pages, last = decode_entry(raw)
        if skey != key:
            raise TierCorrupt("tier spill key mismatch")
        # Promote back to resident (it is about to be device_put anyway;
        # the caller discards on successful swap-in).
        ent.pages, ent.last = pages, last
        self._bytes += ent.nbytes
        self._spilled_bytes -= ent.nbytes
        self._unlink(ent)
        while self._bytes > self.capacity and len(self._entries) > 1:
            self._evict_oldest_resident()
        return ent.length, ent.pages, ent.last

    # -- removal -------------------------------------------------------

    def discard(self, key: Key) -> bool:
        """Drop ``key`` (and any spill file). Returns whether present."""
        ent = self._entries.pop(key, None)
        if ent is None:
            return False
        self._forget(ent)
        return True

    def _forget(self, ent: _Entry) -> None:
        if ent.pages is not None:
            self._bytes -= ent.nbytes
        else:
            self._spilled_bytes -= ent.nbytes
            self._unlink(ent)
        ent.pages = None
        ent.last = None

    @staticmethod
    def _unlink(ent: _Entry) -> None:
        if ent.path is not None:
            try:
                os.unlink(ent.path)
            except OSError:
                pass  # best-effort; a stale spill file is inert
            ent.path = None

    def keys(self) -> list[Key]:
        return list(self._entries)

    # -- accounting ----------------------------------------------------

    def stats(self) -> dict:
        n_pages = sum(e.n_pages for e in self._entries.values())
        return {
            "tier_entries": len(self._entries),
            "tier_bytes": self._bytes,
            "tier_spilled_bytes": self._spilled_bytes,
            "tier_pages": n_pages,
        }
