"""Shared cache-model programs for the serving stack.

ONE definition of "apply the LM against its KV cache" per mode, used by
the continuous-batching engine and speculative decoding alike (each jits
these cores with its own epilogue — argmax for the spec verifier, raw
logits for the engine's sampler — so no cross-module drift in the
prefill/decode/extend semantics is possible).

Also home of the prompt-width bucket policy: server-side validation and
engine admission MUST agree on it, or the server accepts requests the
engine rejects.
"""

from __future__ import annotations

import jax.numpy as jnp

from k3stpu.models.generate import init_cache


def prompt_width_bucket(max_len: int, max_seq: int, floor: int = 8) -> int:
    """Next power of two >= max_len (min ``floor``), capped at the cache —
    the one bucket policy every generate entry point quantizes widths
    with (bounded compiled-program set, reference of truth)."""
    width = 1 << (max(1, max_len) - 1).bit_length()
    return min(max(width, floor), max_seq)


def _akw(adapter_ids, block_tables=None):
    # Multi-LoRA per-row adapter ids and paged-cache block tables:
    # forwarded only when present — both LM families accept the kwargs;
    # this keeps non-adapter, non-paged call signatures identical to the
    # original ones.
    kw = {} if adapter_ids is None else {"adapter_ids": adapter_ids}
    if block_tables is not None:
        kw["block_tables"] = block_tables
    return kw


def prefill_core(model, params, block, lens, adapter_ids=None):
    """Prefill the prompt block: returns ``(cache, last_logits)`` where
    ``last_logits[r]`` is row r's distribution at its last REAL position
    (fp32) — the first-token source for every scheduler."""
    cache = init_cache(model, block.shape[0])
    logits, mut = model.apply({"params": params, "cache": cache}, block,
                              mode="prefill", seq_lens=lens,
                              mutable=["cache"], **_akw(adapter_ids))
    last = jnp.take_along_axis(logits, (lens - 1)[:, None, None],
                               axis=1)[:, 0]
    return mut["cache"], last.astype(jnp.float32)


def decode_core(model, params, cache, toks, adapter_ids=None,
                block_tables=None):
    """One decode step for (B,) tokens: ``(cache, logits (B, V) fp32)``.
    ``block_tables``: page-id map for a paged-cache model (traced)."""
    logits, mut = model.apply({"params": params, "cache": cache},
                              toks[:, None], mode="decode",
                              mutable=["cache"],
                              **_akw(adapter_ids, block_tables))
    return mut["cache"], logits[:, -1].astype(jnp.float32)


def extend_core(model, params, cache, chunk, adapter_ids=None,
                block_tables=None):
    """Chunk-append (B, G) tokens at per-row offsets:
    ``(cache, logits (B, G, V) fp32)`` — logits[:, j] scores the next
    token after chunk[:, :j+1]."""
    logits, mut = model.apply({"params": params, "cache": cache}, chunk,
                              mode="extend", mutable=["cache"],
                              **_akw(adapter_ids, block_tables))
    return mut["cache"], logits.astype(jnp.float32)
