"""JAX inference serving — the workload replacing the reference's Jellyfin
demo (reference jellyfin.yaml:1-43: a long-running Deployment holding one
GPU behind a ClusterIP Service). Here: a batched JAX model server holding one
TPU chip behind a Service (BASELINE.json config 4)."""

from k3stpu.serve.server import InferenceServer, make_app  # noqa: F401
