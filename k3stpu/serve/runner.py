"""Model runner: the engine's jitted device programs (docs/DISAGG.md
names this layer in the decomposed engine).

Every program here is compiled once per static bucket and dispatched by
the scheduler loop (serve/scheduler.py) against the KV state owned by
the page manager (serve/kv_manager.py). ``GenerateEngine`` composes the
three as mixins over one shared ``self`` — the decomposition moves code,
not state, so the bit-exactness suites pin behavior across the split.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from k3stpu.models.generate import set_cache_index
from k3stpu.serve.programs import (
    decode_core,
    extend_core,
    prefill_core,
)

_NEG_INF = -1e30


def _pow2_at_least(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _sample_rows(logits, temps, topks, topps, key):
    """Per-row sampling over (B, V) logits: temperature <= 0 is greedy;
    top-k cuts below each row's own k-th value (k == V disables); top-p
    keeps each row's smallest nucleus reaching mass p (1.0 disables).

    The all-greedy batch — the dominant serving case, and every decode
    step of the exactness-pinned capture runs — skips the sampling
    machinery entirely via ``lax.cond``: the mixed path pays two full
    (B, V) sorts (top-k kth-value + top-p nucleus) per step, pure
    VPU/HBM waste when no row will use the result."""
    from k3stpu.models.generate import top_p_mask

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def mixed(_):
        v = logits.shape[-1]
        scaled = logits / jnp.clip(temps, 1e-6, None)[:, None]
        srt = jnp.sort(scaled, axis=-1)
        kth = jnp.take_along_axis(
            srt, (v - jnp.clip(topks, 1, v))[:, None], axis=-1)
        cut = jnp.where(scaled < kth, _NEG_INF, scaled)
        cut = top_p_mask(cut, topps)
        sampled = jax.random.categorical(key, cut,
                                         axis=-1).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    return jax.lax.cond(jnp.all(temps <= 0.0), lambda _: greedy, mixed,
                        None)


class ModelRunnerMixin:
    """The jitted prefill/decode/extend/spec-verify dispatches plus the
    small helpers that build their traced arguments. Owns no state of
    its own — ``self`` is the composed ``GenerateEngine``."""

    # --- jitted device programs (compiled once per static bucket) -------

    # params travel as jit ARGUMENTS (donated weights would bake into the
    # compiled program as constants otherwise — double the HBM). The
    # cache-model programs themselves are the shared cores in
    # serve/programs.py (one definition for engine + speculative).

    # Tensor parallelism (--tp-shards): when the engine carries a mesh,
    # params arrive sharded Megatron-style (attention heads and MLP
    # hidden split over the 'model' axis — parallel/sharding.py) and
    # every program below is an auto-SPMD program over that mesh. The
    # KV leaves are pinned to their head-axis layout INSIDE the traced
    # program via _tp_constrain, so XLA never round-trips the pool
    # through a replicated layout between the scatter ops and the
    # attention core — each shard reads and writes only its own heads'
    # pages. The per-token all-reduce (attention/MLP output psum) is
    # scheduled by XLA's latency-hiding scheduler, which overlaps it
    # with the NEXT layer's first matmul where the dependency allows.

    def _tp_constrain(self, cache):
        """Pin head-axis sharding on KV leaves inside a jitted program.

        (B, S, H, D) dense rows, (P, ps, H, D) page pools and
        (P, ps, H) int8 scale planes shard on axis 2 when the 'model'
        axis divides it — the SAME predicate the engine's device_put
        uses at init, so constraint and resident layout always agree.
        Indivisible leaves (indices, logits) pass through. No-op (and
        trace-identical to the pre-TP programs) when there is no mesh.
        """
        if self.mesh is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as P

        tp = self.mesh.shape["model"]

        def pin(x):
            if getattr(x, "ndim", 0) >= 3 and x.shape[2] % tp == 0:
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(self.mesh, P(None, None, "model")))
            return x

        return jax.tree.map(pin, cache)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_step(self, params, cache, toks, temps, topks, topps,
                     step, base_key, aids=None):
        cache, logits = decode_core(self.model, params, cache, toks,
                                    adapter_ids=aids)
        key = jax.random.fold_in(base_key, step)
        return cache, _sample_rows(logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 9))
    def _decode_block_step(self, params, cache, toks, temps, topks,
                           topps, step, base_key, k_tokens: int,
                           aids=None):
        """K decode steps in ONE dispatch: ``lax.scan`` over the
        single-token core, sampling on-device each step. Returns the
        (K, B) token block; greedy rows are exactly K steps of argmax,
        so engine output stays pinned to ``generate()`` token for
        token. Rows that finish mid-block keep decoding (static shapes;
        the host discards their surplus) — their cache writes clamp at
        the row's last slot and the slot's next reuse scatters a fresh
        prefill over everything, index included."""
        block_key = jax.random.fold_in(base_key, step)

        def body(carry, i):
            cache, tok = carry
            cache, logits = decode_core(self.model, params, cache, tok,
                                        adapter_ids=aids)
            key = jax.random.fold_in(block_key, i)
            nxt = _sample_rows(logits, temps, topks, topps, key)
            return (cache, nxt), nxt

        (cache, _), out = jax.lax.scan(
            body, (cache, toks), jnp.arange(k_tokens))
        return cache, out

    @functools.partial(jax.jit, static_argnums=(0,))
    def _prefill(self, params, block, lens, aids=None):
        return self._tp_constrain(prefill_core(self.model, params, block,
                                               lens, adapter_ids=aids))

    @functools.partial(jax.jit, static_argnums=(0,))
    def _scatter(self, big, small, slot_ids):
        return jax.tree.map(lambda b, s: b.at[slot_ids].set(s), big, small)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _extend_chunk(self, params, cache, chunk, aids=None):
        return extend_core(self.model, params, cache, chunk,
                           adapter_ids=aids)[0]

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_logits(self, params, cache, toks, aids=None):
        return decode_core(self.model, params, cache, toks,
                           adapter_ids=aids)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _first_sample(self, last_logits, temps, topks, topps, step,
                      base_key):
        key = jax.random.fold_in(base_key, step)
        return _sample_rows(last_logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _broadcast_rows(self, cache, last, n: int):
        """Row 0 of a 1-row admission cache replicated to n rows — the
        shared-prefix fan-out (one prefill, n sampled continuations)."""
        rep = jax.tree.map(
            lambda x: jnp.broadcast_to(x[:1], (n, *x.shape[1:])), cache)
        return rep, jnp.broadcast_to(last[:1], (n, *last.shape[1:]))

    # --- paged-cache programs (block tables + host-injected indices) ----

    # Every paged program takes the host's (slots,) index mirror and
    # stamps it into the cache before the core runs: device-side index
    # state is disposable, so a batch-wide call that advances OTHER
    # rows' indices (the prefix-hit extension neutralizes those rows
    # onto the sink page) is corrected for free at the next dispatch.
    # Block tables are traced int32 data — one compiled program serves
    # every page assignment, zero steady-state recompiles.

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_decode_step(self, params, cache, idx, bts, toks, temps,
                           topks, topps, step, base_key, aids=None):
        cache = self._tp_constrain(set_cache_index(cache, idx))
        cache, logits = decode_core(self.pmodel, params, cache, toks,
                                    adapter_ids=aids, block_tables=bts)
        key = jax.random.fold_in(base_key, step)
        return cache, _sample_rows(logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 11))
    def _paged_decode_block_step(self, params, cache, idx, bts, toks,
                                 temps, topks, topps, step, base_key,
                                 k_tokens: int, aids=None):
        cache = self._tp_constrain(set_cache_index(cache, idx))
        block_key = jax.random.fold_in(base_key, step)

        def body(carry, i):
            cache, tok = carry
            cache, logits = decode_core(self.pmodel, params, cache, tok,
                                        adapter_ids=aids,
                                        block_tables=bts)
            key = jax.random.fold_in(block_key, i)
            nxt = _sample_rows(logits, temps, topks, topps, key)
            return (cache, nxt), nxt

        (cache, _), out = jax.lax.scan(
            body, (cache, toks), jnp.arange(k_tokens))
        return cache, out

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_extend(self, params, cache, idx, bts, chunk, aids=None):
        cache = self._tp_constrain(set_cache_index(cache, idx))
        return extend_core(self.pmodel, params, cache, chunk,
                           adapter_ids=aids, block_tables=bts)[0]

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_decode_logits(self, params, cache, idx, bts, toks,
                             aids=None):
        cache = set_cache_index(cache, idx)
        return decode_core(self.pmodel, params, cache, toks,
                           adapter_ids=aids, block_tables=bts)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _spec_verify(self, params, cache, idx, bts, chunk, aids=None):
        """Speculative verify: ONE extend over the static
        ``(slots, spec_gamma+1)`` chunk ``[x0, d1..d_gamma]``.
        ``logits[:, j]`` scores the token after ``chunk[:, :j+1]``, so
        the row-wise argmax is the target's own greedy continuation at
        every draft position — the host keeps each row's longest
        matching prefix plus the token at the first divergence. The
        argmax epilogue stays in-jit (shipping (slots, G, V) logits to
        the host every dispatch would swamp the win) and is also what
        pins ``speculate=True`` to greedy exactness: there is no
        sampled verify."""
        cache = self._tp_constrain(set_cache_index(cache, idx))
        cache, logits = extend_core(self.pmodel, params, cache, chunk,
                                    adapter_ids=aids, block_tables=bts)
        return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _pack_pages(self, pool, small, page_map):
        """Scatter a dense-prefilled admission cache into the page pool:
        row j's (max_seq,) K/V reshapes into (n_bt, page_size) pages and
        lands at pages ``page_map[j]`` (pad rows map to the sink). One
        compile per admitted-rows bucket; 'index' leaves pass through —
        they are host-injected at every dispatch."""
        dense = {tuple(k.key for k in p): v for p, v
                 in jax.tree_util.tree_flatten_with_path(small)[0]}

        def pack(path, leaf):
            name = path[-1].key
            if not name.endswith("_pages"):
                return leaf
            src = dense[tuple(k.key for k in path[:-1])
                        + (name[:-len("_pages")],)]
            r = src.reshape(src.shape[0], -1, self.page_size,
                            *src.shape[2:])
            return leaf.at[page_map].set(r)

        return jax.tree_util.tree_map_with_path(pack, pool)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _copy_page(self, pool, src, dst):
        """Duplicate ONE page across every layer's pool — the
        copy-on-write behind prefix sharing (a partial tail page gets
        written by its row, so sharers take a private copy). src/dst
        trace: every copy reuses one compiled program."""
        return jax.tree_util.tree_map_with_path(
            lambda p, x: (x.at[dst].set(x[src])
                          if str(getattr(p[-1], "key", "")
                                 ).endswith("_pages") else x),
            pool)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _restore_pages(self, pool, host, page_idx):
        """Tier swap-in scatter: host-gathered page rows (a dict keyed
        by "/"-joined leaf paths, each ``(n, page_size, ...)``) land at
        pages ``page_idx`` across every ``*_pages`` pool leaf in ONE
        dispatch — jit turns the host dict into a single batched
        device_put + scatter. ``n`` is pow2-bucketed by the caller; pad
        rows carry zeros and target the sink page 0 (which absorbs junk
        writes by design), so one compile serves every chain length in
        a bucket."""
        def put(path, leaf):
            if not str(getattr(path[-1], "key", "")).endswith("_pages"):
                return leaf
            key = "/".join(str(getattr(k, "key", k)) for k in path)
            return leaf.at[page_idx].set(host[key])

        return jax.tree_util.tree_map_with_path(put, pool)

    # --- traced-argument helpers ----------------------------------------

    def _aid_arg(self, n: int, adapter: int):
        """(n,)-row adapter-id array for a single request's device call —
        None when the model carries no adapter stacks (exact pre-multi-
        LoRA program signatures)."""
        if self.n_adapters is None:
            return None
        return jnp.full((n,), adapter, jnp.int32)

    def _hit_aids(self, r0: int, adapter: int):
        """(slots,) adapter ids for a batch-wide hit-admission call:
        row r0 uses the request's adapter, other rows keep their live
        values (their output is discarded and their writes are sinked,
        so any valid id works)."""
        if self.n_adapters is None:
            return None
        a = self._aids.copy()
        a[r0] = adapter
        return jnp.asarray(a)

    def _decode_mfu(self, tokens: int, dt: float) -> "float | None":
        """Modeled MFU of one decode dispatch: emitted tokens × modeled
        flops/token over measured wall time, against the device peak.
        None when the peak is unknown (CPU stand-in) or dt is zero."""
        if self._peak_flops is None or dt <= 0:
            return None
        return tokens * self._decode_flops_per_tok / dt / self._peak_flops

    def _record_backend_failure(self) -> None:
        if self.breaker is not None:
            self.breaker.record_failure()
