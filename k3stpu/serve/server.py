"""Batched JAX inference server (ResNet-50, transformer LM, or MoE LM).

Parity with the reference's real workload (reference jellyfin.yaml:1-43):
long-running Deployment, one accelerator, ClusterIP Service in front. TPU-
first serving choices:

- requests are padded to a fixed set of batch sizes (1, 8, 32) so every
  request hits a pre-compiled XLA program — no recompiles in steady state
  (batch=32 is BASELINE.json config 4's shape);
- concurrent /v1/predict requests COALESCE: a dispatcher thread collects
  requests arriving within a short window (--batch-window-ms, default 5)
  into one padded forward, so 8 concurrent batch-1 clients cost one
  batch-8 program, not 8 serialized batch-1 programs — the TPU-first
  answer to a one-chip singleton behind a Service (MXU utilization scales
  with batch; dispatch overhead does not);
- the model runs in bf16 with fp32 logits; weights initialize once at boot
  (the reference's Jellyfin similarly carries its state in-image — no volume,
  jellyfin.yaml:24-29);
- stdlib http.server (threaded) keeps the image dependency-free; the JAX
  dispatch itself is serialized by a lock, matching one-chip ownership;
- /v1/models reports live examples/s and tokens/s (computed over device-busy
  time) plus the dispatch count, so the coalescing win is observable.

Endpoints:
  GET  /healthz         -> {"ok": true, "devices": [...]}   (readiness:
                           503 while draining / breaker open / loop dead)
  GET  /livez           -> {"ok": true}                      (liveness)
  GET  /v1/models       -> model card
  GET  /metrics         -> Prometheus counters (scrape surface)
  POST /v1/predict      -> {"inputs": [...]} -> logits/top-k
  POST /v1/score        -> {"tokens": [[...]]} -> per-token logprobs + NLL
  POST /v1/generate     -> {"prompt_tokens": [[...]], "max_new_tokens": N,
                            "temperature": t, "top_k": k, "top_p": p,
                            "eos_id": e, "num_samples": n}
                        -> {"tokens": [[...]]}  (LM families only;
                           KV-cache prefill + lax.scan decode)

Run: python -m k3stpu.serve.server --model resnet50 --port 8096
(8096 mirrors the reference Service port, jellyfin.yaml:40-42.)
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from urllib.parse import parse_qs, urlparse

from k3stpu.obs import (ServeObs, format_traceparent, new_span_id,
                        new_trace_id, parse_traceparent,
                        prometheus_text_to_openmetrics)

BATCH_SIZES = (1, 8, 32)

# Canary probes (k3stpu.canary) mark themselves with this request
# header; the handler turns it into the ``synthetic=True`` kwarg so the
# request runs the ordinary serving path but its latencies stay out of
# the organic histograms (SLO / autoscaler inputs).
CANARY_HEADER = "X-K3STPU-Canary"

# QoS priority class (docs/QOS.md): the router forwards it, the handler
# turns it into the engine's ``priority`` kwarg. The JSON body's
# ``priority`` field wins over the header (the header is the router's
# channel; the body is the client's).
PRIORITY_HEADER = "X-K3STPU-Priority"


def lm_base_cfg(cfg):
    """The TransformerConfig that actually carries the LM knobs: MoE
    nests it under .base, the dense family IS it. The single read-side
    helper — reading a knob off a MoeConfig directly returns the
    default and silently mis-serves (the multi_lora lookup did exactly
    that)."""
    return getattr(cfg, "base", cfg)


def lm_cfg_replace(model_name: str, cfg, **kw):
    """dataclasses.replace on the LM knobs, nesting under .base for the
    MoE family — the single write-side helper for the same pattern."""
    import dataclasses

    if model_name.startswith("moe"):
        return dataclasses.replace(
            cfg, base=dataclasses.replace(cfg.base, **kw))
    return dataclasses.replace(cfg, **kw)


def served_batch(n: int) -> int:
    """Smallest served (pre-compilable) batch size >= n — the padding
    policy for every dispatch path; public so tools (loadgen) can warm
    exactly the sizes a given load will hit."""
    padded = next((b for b in BATCH_SIZES if b >= n), None)
    if padded is None:
        raise ValueError(
            f"batch {n} exceeds max served batch {BATCH_SIZES[-1]}")
    return padded


class MicroBatcher:
    """Coalesces concurrent predict() calls into one padded device batch.

    Request threads submit() and block; a single dispatcher thread takes the
    first waiting request, keeps collecting until the window closes or the
    max batch fills, runs ONE forward over the concatenation, and scatters
    result slices back. A request that would overflow the max batch is
    carried into the next round (never split — callers get exactly their
    rows back). A batch-level failure propagates to every caller in it.
    """

    def __init__(self, run_batch, window_s: float = 0.005,
                 max_batch: int = BATCH_SIZES[-1]):
        self._run_batch = run_batch  # (np.ndarray, n_requests) -> np.ndarray
        self._window_s = window_s
        self._max = max_batch
        self._q: "queue.SimpleQueue[dict | None]" = queue.SimpleQueue()
        self._carry: dict | None = None
        self._closed = False
        self._dead: "BaseException | None" = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="microbatcher")
        self._thread.start()

    def close(self) -> None:
        """Stop the dispatcher thread (it exits after draining in-flight
        work). Without this the daemon thread pins the server — and its
        weights — for the life of the process."""
        self._closed = True
        self._q.put(None)

    def submit(self, inputs: np.ndarray) -> np.ndarray:
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        item = {"inputs": inputs, "event": threading.Event(),
                "result": None, "error": None}
        self._q.put(item)
        # A bounded wait + liveness re-check: a submit racing close() can
        # land its item behind the shutdown sentinel, and a dispatcher
        # that DIED (an exception escaping _run, not just a group
        # failure) will never set the event — an unbounded wait would
        # strand this thread forever. Death propagates immediately; on a
        # clean close, grant one grace period so a request the dispatcher
        # already picked up can still deliver its result.
        while not item["event"].wait(timeout=0.2):
            dead = self._dead
            if dead is not None or not self._thread.is_alive():
                if item["event"].is_set():  # died AFTER serving this item
                    break
                if dead is None and self._closed:
                    raise RuntimeError(
                        "MicroBatcher closed with request in flight")
                raise RuntimeError(
                    f"MicroBatcher dispatcher thread died: {dead!r}"
                ) from dead
            if self._closed:
                if item["event"].wait(timeout=30.0):
                    break
                raise RuntimeError(
                    "MicroBatcher closed with request in flight")
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    def _gather(self) -> "list[dict] | None":
        first = self._carry if self._carry is not None else self._q.get()
        self._carry = None
        if first is None:  # close() sentinel
            return None
        items, rows = [first], len(first["inputs"])
        deadline = time.perf_counter() + self._window_s
        while rows < self._max:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is None:
                self._carry = None  # drop sentinel; loop exits next round
                self._q.put(None)
                break
            if rows + len(nxt["inputs"]) > self._max:
                self._carry = nxt  # head-of-line for the next round
                break
            items.append(nxt)
            rows += len(nxt["inputs"])
        return items

    def _loop(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 — propagate death to waiters
            # Set _dead BEFORE draining: an item enqueued after the drain
            # still sees _dead on its submit()'s next wait tick, so there
            # is no window where a waiter can strand.
            self._dead = e
            err = RuntimeError(f"MicroBatcher dispatcher thread died: {e!r}")
            items = [self._carry] if self._carry is not None else []
            self._carry = None
            try:
                while True:
                    it = self._q.get(block=False)
                    if it is not None:
                        items.append(it)
            except queue.Empty:
                pass
            for it in items:
                it["error"] = err
                it["event"].set()

    def _run(self) -> None:
        while True:
            items = self._gather()
            if items is None:
                return
            # One dispatch per trailing shape: /v1/score submits
            # width-bucketed blocks (e.g. (n, 8)) through the same batcher
            # as full-width /v1/predict rows — concatenating across widths
            # would raise and fail every coalesced caller. Same-shape
            # requests still coalesce; a mixed window costs one extra
            # dispatch, and a failure only fails its own shape group.
            groups: "dict[tuple, list[dict]]" = {}
            for it in items:
                groups.setdefault(it["inputs"].shape[1:], []).append(it)
            for group in groups.values():
                try:
                    batch = (np.concatenate([it["inputs"] for it in group])
                             if len(group) > 1 else group[0]["inputs"])
                    out = self._run_batch(batch, len(group))
                    ofs = 0
                    for it in group:
                        k = len(it["inputs"])
                        it["result"] = out[ofs:ofs + k]
                        ofs += k
                except Exception as e:  # noqa: BLE001 — fail the group, not the loop
                    for it in group:
                        it["error"] = e
                finally:
                    # Release only waiters that reached a terminal state.
                    # A BaseException escaping the group (dispatcher
                    # death) must NOT set bare events here — that would
                    # hand those callers a silent None result; they are
                    # failed by the _loop death handler / the _dead
                    # check in submit() instead.
                    for it in group:
                        if it["result"] is not None or it["error"] is not None:
                            it["event"].set()


class InferenceServer:
    """Owns the model, its weights, and the jitted per-batch-size programs."""

    def __init__(self, model_name: str = "resnet50", num_classes: int = 1000,
                 image_size: int = 224, seq_len: int = 128,
                 batch_window_ms: float = 5.0,
                 shard_devices: "int | None" = None,
                 tp_shards: int = 1,
                 ckpt_dir: "str | None" = None,
                 ckpt_step: "int | None" = None,
                 quant: "str | None" = None,
                 kv_cache_dtype: "str | None" = None,
                 continuous_batching: bool = False,
                 engine_slots: int = 8,
                 prefill_chunk: "int | None" = None,
                 decode_block: int = 4,
                 prompt_cache: int = 0,
                 max_pending: "int | None" = None,
                 kv_page_size: "int | None" = None,
                 kv_pages: "int | None" = None,
                 attn_backend: str = "xla-gather",
                 lora_adapters: "str | None" = None,
                 draft_model: "str | None" = None,
                 draft_ckpt_dir: "str | None" = None,
                 speculate: bool = False,
                 spec_gamma: int = 4,
                 tier_host_mb: "int | None" = None,
                 tier_dir: "str | None" = None,
                 tier_watermark: int = 0,
                 watchdog_s: "float | None" = 120.0,
                 breaker_threshold: "int | None" = 5,
                 breaker_cooldown_s: float = 5.0,
                 instance: "str | None" = None,
                 role: str = "monolithic",
                 prefill_upstream: "str | None" = None,
                 chaos=None,
                 qos: bool = False,
                 qos_classes: str = "interactive,batch",
                 interactive_ttft_slo_ms: float = 2500.0,
                 batch_ttft_slo_ms: float = 30000.0):
        """``shard_devices``: tensor-parallel serving over that many local
        devices (the multi-chip-pod workload — a pod requesting
        ``google.com/tpu: 4`` shards the model across its 4 chips; the
        plugin's GetPreferredAllocation already made them ICI-adjacent).
        None = all local devices when there are several, else single.

        ``tp_shards``: the EXPLICIT tensor-parallel width (--tp-shards,
        the chart's inference.tpShards). Functionally it pins
        shard_devices to N (the two must agree if both given), and it
        additionally arms the TP observability surface — the
        k3stpu_serve_tp_* families, the tp_shards build_info label, the
        per-shard pages-free series, and the engine's head-divisibility
        validation. Default 1 leaves every exposition byte identical to
        the pre-TP server, even on a multi-device host where
        shard_devices still auto-shards the mesh."""
        import jax

        self.model_name = model_name
        self.image_size = image_size
        self.seq_len = seq_len
        # Replica identity (pod name or host:port): stamped on every
        # HTTP response as X-K3STPU-Replica and — when explicitly
        # configured — as the instance label on k3stpu_build_info, so
        # the router tier, traces, and loadgen can name which replica
        # served a request. The fallback hostname keeps the header
        # meaningful for library/test constructions without touching
        # their exposition's label set.
        import socket

        self.instance = instance or socket.gethostname()
        # Disaggregated prefill/decode serving (docs/DISAGG.md). A
        # prefill-role replica answers /v1/prefill with serialized KV
        # page chains; a decode-role replica pulls a chain from its
        # prefill peer (the router's X-K3STPU-Prefill-Endpoint header,
        # or --prefill-upstream) before admitting a generate request,
        # so the admission is an exact prompt-cache hit and decode
        # never pays prefill interference. Monolithic (the default)
        # changes nothing anywhere — same exposition bytes, same paths.
        if role not in ("monolithic", "prefill", "decode"):
            raise ValueError(f"role must be monolithic, prefill, or "
                             f"decode, got {role!r}")
        if role != "monolithic" and (
                not continuous_batching or kv_page_size is None
                or prompt_cache <= 0):
            raise ValueError(
                "--role prefill/decode requires --continuous-batching, "
                "--kv-page-size, and --prompt-cache > 0: the disagg KV "
                "handoff stages page chains through the paged prompt "
                "cache on both sides")
        if prefill_upstream is not None and role != "decode":
            raise ValueError(
                "--prefill-upstream only applies to --role decode (it "
                "names the prefill peer a decode replica pulls from)")
        self.role = role
        self._prefill_upstream = prefill_upstream
        self._prefill_timeout_s = 30.0
        # Tensor-parallel width (docs/ARCHITECTURE.md HBM sizing). The
        # explicit knob both pins the mesh width and opts in to the TP
        # exposition; auto-sharding alone (multi-device host, no flag)
        # keeps the monolithic exposition byte-stable.
        if tp_shards < 1:
            raise ValueError(f"--tp-shards must be >= 1, got {tp_shards}")
        if tp_shards > 1:
            if shard_devices is not None and shard_devices != tp_shards:
                raise ValueError(
                    f"--tp-shards {tp_shards} disagrees with "
                    f"--shard-devices {shard_devices}")
            shard_devices = tp_shards
        self.tp_shards = tp_shards
        # SLO-aware QoS (docs/QOS.md): priority classes + predictive
        # admission + loss-free preemption. Engine-loop features, so the
        # flag requires continuous batching; default off keeps the
        # classless exposition byte-stable.
        if qos and not continuous_batching:
            raise ValueError(
                "--qos requires --continuous-batching: priority classes, "
                "predictive admission, and preemption are engine-loop "
                "features")
        self.qos = bool(qos)
        self.qos_classes = tuple(
            c.strip() for c in qos_classes.split(",") if c.strip())
        if qos and self.qos_classes != ("interactive", "batch"):
            raise ValueError(
                f"--qos-classes must be 'interactive,batch' (the only "
                f"supported class set), got {qos_classes!r}")
        self.interactive_ttft_slo_ms = float(interactive_ttft_slo_ms)
        self.batch_ttft_slo_ms = float(batch_ttft_slo_ms)
        # Two locks with distinct jobs: _lock serializes DEVICE dispatch
        # ("one chip, one queue" — held for whole generations), while
        # _stats_lock guards only the counters, so /metrics scrapes and
        # /v1/models reads never stall behind an in-flight generation.
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()
        # predict and generate keep DISJOINT counters: predict throughput
        # (examples/seconds/dispatches — the micro-batching metrics) must
        # not be diluted by generate traffic, whose cost scales with tokens.
        self._stats = {"requests": 0, "examples": 0, "dispatches": 0,
                       "seconds": 0.0, "gen_requests": 0, "gen_examples": 0,
                       "tokens": 0, "gen_seconds": 0.0}
        self._gen_counter = 0  # per-request sampling key ordinal
        # Request-lifecycle traces + latency histograms (k3stpu/obs).
        # ONE instance feeds /metrics, /debug/requests, /debug/trace —
        # and the engine loop's hooks when continuous batching is on.
        self._obs = ServeObs(instance=instance, attn_backend=attn_backend,
                             role=None if role == "monolithic" else role,
                             tp_shards=tp_shards if tp_shards > 1 else None)
        self._profile_lock = threading.Lock()  # one /debug/profile at a time
        # Failure containment (docs/RESILIENCE.md): the engine-facing
        # knobs default ON here (the HTTP server is the production
        # surface) and OFF in GenerateEngine itself (library/bench use).
        self._breaker = None
        self._chaos = chaos  # k3stpu.chaos.FaultInjector | None
        self._watchdog_s = watchdog_s
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        # Graceful drain: begin_drain() flips /healthz not-ready and 503s
        # new /v1 work; _active_http tracks in-flight handler threads so
        # main()'s drainer knows when the last response has gone out.
        self._draining = False
        self._active_http = 0  # guarded by _stats_lock

        if model_name == "resnet50":
            from k3stpu.models.resnet import resnet50

            self.model = resnet50(num_classes=num_classes)
            example = np.zeros((1, image_size, image_size, 3), np.float32)
        elif model_name == "transformer":
            from k3stpu.models.transformer import transformer_lm_small

            self.model = transformer_lm_small(max_seq_len=seq_len)
            example = np.zeros((1, seq_len), np.int32)
        elif model_name == "transformer-medium":
            # The train flagship (~350M): what train_job --model medium
            # checkpoints, servable through the same train->serve loop.
            from k3stpu.models.transformer import transformer_lm_medium

            self.model = transformer_lm_medium(max_seq_len=max(seq_len, 512))
            example = np.zeros((1, seq_len), np.int32)
        elif model_name == "transformer-tiny":  # tests / CPU smoke
            from k3stpu.models.transformer import transformer_lm_tiny

            self.model = transformer_lm_tiny(max_seq_len=seq_len)
            example = np.zeros((1, seq_len), np.int32)
        elif model_name == "moe":
            from k3stpu.models.moe import moe_lm_small

            self.model = moe_lm_small(max_seq_len=seq_len)
            example = np.zeros((1, seq_len), np.int32)
        elif model_name == "moe-tiny":  # tests / CPU smoke
            from k3stpu.models.moe import moe_lm_tiny

            self.model = moe_lm_tiny(max_seq_len=seq_len)
            example = np.zeros((1, seq_len), np.int32)
        elif model_name == "resnet18-tiny":  # tests / CPU smoke
            from k3stpu.models.resnet import resnet18

            self.model = resnet18(num_classes=num_classes)
            example = np.zeros((1, image_size, image_size, 3), np.float32)
        else:
            raise ValueError(f"unknown model {model_name!r}")

        self._variables = self.model.init(jax.random.key(0), example[:1],
                                          train=False)

        # Serve trained weights: restore params from a train_job checkpoint
        # (volume/GCS mount — the train -> checkpoint -> serve loop). The
        # freshly-initialized tree is the restore target, so architecture
        # mismatches fail loudly at boot, not at first request.
        self.loaded_step: "int | None" = None
        if ckpt_dir is not None:
            from k3stpu.utils import checkpoint as ckpt

            import jax.numpy as jnp

            step = ckpt_step if ckpt_step is not None \
                else ckpt.latest_step(ckpt_dir)
            if step is None:
                raise ValueError(f"no finalized checkpoint under {ckpt_dir}")
            # Partial restore: only the serving collections are read (the
            # optimizer state — ~2x params under adamw — never touches
            # boot I/O). Structure mismatches raise inside orbax.
            want = {coll: tree for coll, tree in self._variables.items()
                    if coll in ("params", "batch_stats")}
            if not want.get("params"):
                raise ValueError("model has no params tree to restore into")

            # LoRA checkpoints (train_job --lora-rank) carry the learning
            # in adapter leaves a base-shaped partial restore would
            # SILENTLY DROP — serving the frozen base as if it were the
            # fine-tune. Sniff the checkpoint's structure (metadata, no
            # data reads), restore the adapter-shaped tree, and fold the
            # delta into the kernels before adoption.
            lora_rank = self._lora_rank_in(
                ckpt.tree_metadata(ckpt_dir, step))
            if lora_rank is not None:
                from k3stpu.models.lora import merge_lora_params

                lmodel = type(self.model)(lm_cfg_replace(
                    model_name, self.model.config, lora_rank=lora_rank))
                lvars = lmodel.init(jax.random.key(0), example[:1],
                                    train=False)
                want = dict(want, params=lvars["params"])
                state = ckpt.restore_collections(ckpt_dir, step, want)
                state = dict(state,
                             params=merge_lora_params(state["params"]))
                print(f"merged rank-{lora_rank} LoRA adapters from "
                      f"checkpoint step {step}", flush=True)
            else:
                state = ckpt.restore_collections(ckpt_dir, step, want)

            def adopt(init, new):
                new = jnp.asarray(new, init.dtype)
                if new.shape != init.shape:
                    # Same tree, different hyperparameters (seq len, vocab,
                    # widths): fail at boot, not at first request.
                    raise ValueError(
                        f"checkpoint leaf shape {new.shape} != model's "
                        f"{init.shape} — wrong architecture/config for "
                        f"--ckpt-dir {ckpt_dir}")
                return new

            merged = dict(self._variables)
            for coll, tree in state.items():
                merged[coll] = jax.tree.map(adopt, merged[coll], tree)
            self._variables = merged
            self.loaded_step = step

        # Multi-LoRA serving (S-LoRA pattern, models/lora.py
        # MultiLoraDense): load N trained adapter checkpoints into
        # stacked per-projection deltas, each request routing to its
        # adapter by name — one base model, one decode batch, many
        # fine-tunes. Runs AFTER base-checkpoint adoption (the stacks
        # attach to the weights actually served) and BEFORE quant
        # (exclusive) / sharding (lora_a replicates, lora_b shards its
        # output axis — parallel/sharding.py).
        self.adapter_names: "list[str] | None" = None
        if lora_adapters:
            if not model_name.startswith(("transformer", "moe")):
                raise ValueError("--lora-adapters supports the LM "
                                 "families (dense transformer and MoE)")
            if quant is not None:
                raise ValueError("--lora-adapters and --quant are "
                                 "exclusive: adapters stay low-rank float")
            import jax.numpy as jnp

            from k3stpu.models.lora import build_multi_lora_params
            from k3stpu.utils import checkpoint as ckpt

            pairs = []
            for spec in lora_adapters.split(","):
                if "=" not in spec:
                    raise ValueError(
                        f"--lora-adapters entry {spec!r}: want name=dir")
                name, d = (t.strip() for t in spec.split("=", 1))
                pairs.append((name, d))
            names = [n for n, _ in pairs]
            if len(set(names)) != len(names) or "base" in names:
                raise ValueError("adapter names must be unique and not "
                                 "'base' (reserved for adapter slot 0)")
            rank = None
            steps = []
            for name, d in pairs:
                astep = ckpt.latest_step(d)
                if astep is None:
                    raise ValueError(f"adapter {name}: no finalized "
                                     f"checkpoint under {d}")
                r = self._lora_rank_in(ckpt.tree_metadata(d, astep))
                if r is None:
                    raise ValueError(f"adapter {name}: checkpoint under "
                                     f"{d} carries no lora_a/lora_b "
                                     f"leaves (not a --lora-rank run?)")
                if rank is None:
                    rank = r
                elif r != rank:
                    raise ValueError(
                        f"adapter {name} has rank {r}, first adapter has "
                        f"{rank} — one shared rank per serving process")
                steps.append(astep)
            # ONE restore template for every adapter (ranks are equal by
            # the check above), and shape-only — eval_shape materializes
            # no weights for a tree that exists just to type the restore.
            lmodel = type(self.model)(lm_cfg_replace(
                model_name, self.model.config, lora_rank=rank))
            lvars = jax.eval_shape(
                lambda: lmodel.init(jax.random.key(0), example[:1],
                                    train=False))
            adapters = [
                ckpt.restore_collections(d, astep,
                                         {"params": lvars["params"]})
                ["params"]
                for (name, d), astep in zip(pairs, steps)]
            self.model = type(self.model)(lm_cfg_replace(
                model_name, self.model.config, lora_rank=rank,
                multi_lora=len(pairs) + 1))
            mlvars = self.model.init(jax.random.key(0), example[:1],
                                     train=False)
            built = build_multi_lora_params(self._variables["params"],
                                            adapters)

            def adopt_ml(init, new):
                new = jnp.asarray(new, init.dtype)
                if new.shape != init.shape:
                    raise ValueError(
                        f"adapter leaf shape {new.shape} != model's "
                        f"{init.shape} — adapters must be trained from "
                        f"this base architecture")
                return new

            self._variables = {
                **self._variables,
                "params": jax.tree.map(adopt_ml, mlvars["params"], built),
            }
            self.adapter_names = names
            print(f"loaded {len(names)} rank-{rank} LoRA adapter(s): "
                  f"{', '.join(names)}", flush=True)

        # Weight-only int8 (models/quant.py): swap the float projection
        # kernels for int8+scale AFTER checkpoint adoption (quantize what
        # will actually be served) and rebuild the model in its quant
        # config — every downstream path (predict, generate, warmup) then
        # runs the dequant-fused matmuls with no further branching.
        self.quant = quant
        self.float_param_bytes: "int | None" = None
        if quant is not None:
            if not model_name.startswith(("transformer", "moe")):
                raise ValueError(
                    f"--quant int8 supports the LM families; "
                    f"{model_name!r} stays float")
            from k3stpu.models.quant import param_bytes, quantize_lm_params

            self.float_param_bytes = param_bytes(self._variables["params"])
            self._variables = {
                **self._variables,
                "params": quantize_lm_params(self._variables["params"]),
            }
            self.model = type(self.model)(
                lm_cfg_replace(model_name, self.model.config, quant=quant))

        # int8 KV cache (no param change — the cache collection is built
        # per generate call from the live config): halves the HBM the
        # serving chip spends per cached token, i.e. doubles the context
        # length x batch ceiling. Orthogonal to --quant.
        self.kv_cache_dtype = kv_cache_dtype
        if kv_cache_dtype is not None:
            if not model_name.startswith(("transformer", "moe")):
                raise ValueError(
                    f"--kv-cache-dtype applies to LM families, not "
                    f"{model_name!r}")
            self.model = type(self.model)(lm_cfg_replace(
                model_name, self.model.config,
                kv_cache_dtype=kv_cache_dtype))

        n_local = len(jax.local_devices())
        if shard_devices is None:
            shard_devices = n_local if n_local > 1 else 1
        if tp_shards > n_local:
            raise ValueError(
                f"--tp-shards {tp_shards} exceeds the {n_local} local "
                f"device(s) this replica holds (the chart's "
                f"inference.tpShards sets the pod's google.com/tpu "
                f"resource count to match)")
        self._mesh = None
        if shard_devices > 1:
            from k3stpu.parallel.mesh import make_mesh
            from k3stpu.parallel.sharding import replicated, shard_params

            # Pure tensor parallelism: every weight's feature axis splits
            # over 'model' (parallel/sharding.py rules); XLA partitions the
            # matmuls/convs and inserts the ICI collectives itself. Inputs
            # and logits stay replicated — each request already fits one
            # chip, the chips pool their FLOPs and HBM.
            # Local devices only: under jax.distributed, jax.devices() is
            # the global list and would hand this pod another host's chips.
            self._mesh = make_mesh(shard_devices,
                                   model_parallelism=shard_devices,
                                   devices=jax.local_devices())
            self._variables = shard_params(self._variables, self._mesh)[0]
            repl = replicated(self._mesh)
            self._forward = jax.jit(
                lambda x: self.model.apply(self._variables, x, train=False),
                in_shardings=(repl,), out_shardings=repl)
        else:
            self._forward = jax.jit(
                lambda x: self.model.apply(self._variables, x, train=False))
        # batch_window_ms == 0 disables cross-request coalescing (each
        # request runs its own padded forward — the pre-coalescing behavior,
        # kept as the loadgen baseline).
        self._batcher = (MicroBatcher(self._run_forward,
                                      window_s=batch_window_ms / 1e3)
                         if batch_window_ms > 0 else None)

        # Continuous batching (serve/engine.py): concurrent /v1/generate
        # requests share one slot-based decode loop — a new request joins
        # mid-flight instead of queueing behind a long generation.
        self._engine = None
        if kv_page_size is not None and not continuous_batching:
            # The page pool lives inside the engine; without it the flag
            # would silently do nothing.
            raise ValueError(
                "--kv-page-size requires --continuous-batching")
        if attn_backend != "xla-gather" and kv_page_size is None:
            # The kernel walks block tables; without a paged pool there
            # is nothing for it to walk.
            raise ValueError(
                f"--attn-backend {attn_backend} requires --kv-page-size "
                f"(the paged Pallas kernel reads the page pool through "
                f"block tables; the dense cache has none)")
        self.attn_backend = attn_backend
        if speculate and not continuous_batching:
            raise ValueError(
                "--speculate is the engine's n-gram draft-then-verify "
                "path; it requires --continuous-batching (and a paged "
                "pool via --kv-page-size). For the two-model form use "
                "--draft-model instead.")
        if speculate and kv_page_size is None:
            raise ValueError(
                "--speculate requires --kv-page-size: speculative "
                "rollback rides the paged cache's host-mirrored "
                "per-row index")
        # Host KV page tier (serve/tiering.py, docs/TIERING.md): parked
        # session chains leave the device pool for host RAM and restore
        # bit-exactly on the session's next turn.
        self._tier = None
        if tier_host_mb is not None and kv_page_size is None:
            raise ValueError(
                "--tier-host-mb requires --kv-page-size: the host tier "
                "parks paged chains; a dense cache has none to park")
        if tier_host_mb is not None and prompt_cache <= 0:
            raise ValueError(
                "--tier-host-mb requires --prompt-cache > 0: restored "
                "chains re-enter the engine as prompt-cache entries")
        if tier_dir is not None and tier_host_mb is None:
            raise ValueError("--tier-dir requires --tier-host-mb")
        if tier_watermark and tier_host_mb is None:
            raise ValueError("--tier-watermark requires --tier-host-mb")
        if continuous_batching:
            if not model_name.startswith(("transformer", "moe")):
                raise ValueError(
                    "--continuous-batching applies to LM families, not "
                    f"{model_name!r}")
            from k3stpu.serve.containment import CircuitBreaker
            from k3stpu.serve.engine import GenerateEngine

            if breaker_threshold is not None:
                self._breaker = CircuitBreaker(
                    threshold=breaker_threshold,
                    cooldown_s=breaker_cooldown_s)
            if tier_host_mb is not None:
                from k3stpu.serve.tiering import HostPageStore

                self._tier = HostPageStore(tier_host_mb * (1 << 20),
                                           spill_dir=tier_dir)
            self._engine = GenerateEngine(
                self.model, self._variables["params"], slots=engine_slots,
                chunk_prefill=prefill_chunk, decode_block=decode_block,
                prompt_cache=prompt_cache, mesh=self._mesh,
                tp_shards=tp_shards,
                max_pending=max_pending, page_size=kv_page_size,
                num_pages=kv_pages, attn_backend=attn_backend,
                speculate=speculate,
                spec_gamma=spec_gamma, obs=self._obs,
                breaker=self._breaker, watchdog_s=watchdog_s,
                chaos=chaos, tier=self._tier,
                tier_watermark=tier_watermark, qos=qos,
                interactive_ttft_slo_s=interactive_ttft_slo_ms / 1000.0,
                batch_ttft_slo_s=batch_ttft_slo_ms / 1000.0)

        # Speculative decoding (serve/speculative.py): greedy /v1/generate
        # requests draft with a small model and verify whole proposal
        # chunks in one target `extend` — fewer HBM-bound target steps,
        # identical output. Sampled requests fall back to the plain path.
        self._draft = None
        self.spec_gamma = spec_gamma
        self._spec_stats = {"requests": 0, "proposed": 0, "accepted": 0}
        if draft_model is not None and spec_gamma < 1:
            # Fail at boot: a bad gamma would otherwise 400 every greedy
            # generate while /healthz keeps passing.
            raise ValueError(f"spec_gamma must be >= 1, got {spec_gamma}")
        if draft_model is not None:
            if not model_name.startswith("transformer"):
                raise ValueError(
                    "--draft-model pairs with the transformer LM family, "
                    f"not {model_name!r}")
            if self._engine is not None:
                raise ValueError(
                    "--draft-model and --continuous-batching are separate "
                    "decode schedulers; pick one")
            draft = InferenceServer(
                model_name=draft_model, seq_len=seq_len,
                batch_window_ms=0.0, shard_devices=1,
                ckpt_dir=draft_ckpt_dir)
            self._draft = (draft.model, draft._variables["params"])
            draft.close()

    def warmup(self, batch_sizes=BATCH_SIZES):
        """Pre-compile every served batch size so first requests are fast.

        LM families also warm the generation path (prefill + decode — and
        through it the engine/speculative programs when configured), so a
        pod is genuinely ready when the readiness probe passes, not just
        for /v1/predict. Resets the stats afterwards: warmup dispatches
        are dominated by JIT compile time and would poison the /v1/models
        throughput numbers (which loadgen commits as the artifact)."""
        for b in batch_sizes:
            self.predict(np.zeros((b, *self.input_shape()), self.input_dtype()))
        if self.model_name.startswith(("transformer", "moe")):
            self.generate_tokens([[1]], max_new_tokens=2)
        self.reset_stats()

    def reset_stats(self) -> None:
        """Zero every throughput counter (server, engine, spec). Callers
        that warm compile paths themselves (loadgen's generate warmup)
        must reset too, or the compile-dominated dispatches poison the
        committed tokens/s."""
        if self._engine is not None:
            self._engine.reset_stats()  # resets the shared obs too
        else:
            self._obs.reset()
        with self._stats_lock:
            for k in self._stats:
                self._stats[k] = type(self._stats[k])()
            for k in self._spec_stats:
                self._spec_stats[k] = 0

    def input_shape(self):
        if self.model_name.startswith("resnet"):
            return (self.image_size, self.image_size, 3)
        return (self.seq_len,)

    def input_dtype(self):
        return np.float32 if self.model_name.startswith("resnet") else np.int32


    def _run_forward(self, inputs: np.ndarray, n_requests: int = 1
                     ) -> np.ndarray:
        """One device dispatch: pad rows to the next served batch size, run
        the jitted program, slice the padding back off. Called by the
        micro-batcher's dispatcher thread (or directly when coalescing is
        off); `inputs` rows may span several coalesced requests."""
        import jax

        n = inputs.shape[0]
        padded = served_batch(n)
        if padded != n:
            pad = np.zeros((padded - n, *inputs.shape[1:]), inputs.dtype)
            inputs = np.concatenate([inputs, pad], axis=0)

        t0 = time.perf_counter()
        with self._lock:  # one chip, one queue
            out = np.asarray(jax.block_until_ready(self._forward(inputs)))
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["requests"] += n_requests
            self._stats["examples"] += n
            self._stats["dispatches"] += 1
            self._stats["seconds"] += dt
        return out[:n]

    def predict(self, inputs: np.ndarray) -> np.ndarray:
        """Predict a batch; concurrent callers coalesce into shared device
        batches when the micro-batcher is on (see MicroBatcher)."""
        served_batch(inputs.shape[0])  # reject oversize before queueing
        if self._batcher is not None:
            return self._batcher.submit(inputs)
        return self._run_forward(inputs)

    def score_tokens(self, token_lists: "list[list[int]]"
                     ) -> "list[list[float]]":
        """Per-token log-probabilities for given sequences (LM families):
        out[r][i] = log P(tokens[r][i+1] | tokens[r][:i+1]) — the scoring
        primitive behind reranking and perplexity evaluation. Rides the
        same padded-bucket forward as /v1/predict (one teacher-forced
        pass, no decode loop)."""
        if not self.model_name.startswith(("transformer", "moe")):
            raise ValueError(f"{self.model_name} is not a generative LM")
        if not token_lists or any(len(t) < 2 for t in token_lists):
            raise ValueError("each sequence needs at least 2 tokens")
        lens = [len(t) for t in token_lists]
        if max(lens) > self.seq_len:
            raise ValueError(
                f"sequence length {max(lens)} exceeds max seq "
                f"{self.seq_len}")
        n = len(token_lists)
        batch = served_batch(n)
        from k3stpu.serve.programs import prompt_width_bucket

        width = prompt_width_bucket(max(lens), self.seq_len)
        block = np.zeros((batch, width), np.int32)
        for i, t in enumerate(token_lists):
            block[i, :len(t)] = t
        logits = self.predict(block)          # (batch, width, V) fp32
        logits = np.asarray(logits, np.float32)
        # log softmax per position, gathered at the NEXT token.
        m = logits.max(axis=-1, keepdims=True)
        logz = m[..., 0] + np.log(
            np.exp(logits - m).sum(axis=-1))  # (batch, width)
        out = []
        for r, toks in enumerate(token_lists):
            idx = np.asarray(toks[1:], np.int64)
            picked = logits[r, np.arange(len(idx)), idx]
            out.append((picked - logz[r, :len(idx)]).tolist())
        return out

    def close(self) -> None:
        """Release the dispatcher/engine threads (embedders/tests; the
        serving process itself runs until killed)."""
        if self._batcher is not None:
            self._batcher.close()
        if self._engine is not None:
            self._engine.close()

    # --- failure containment (docs/RESILIENCE.md) -----------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """SIGTERM path: /healthz goes not-ready (endpoint removal) and
        new /v1 work gets 503 + Retry-After; in-flight requests finish."""
        self._draining = True

    def health(self) -> "tuple[bool, str]":
        """Readiness (NOT liveness — that's /livez): False pulls the pod
        from Service rotation. Half-open is reported READY on purpose:
        the breaker's probe request has to arrive through the Service,
        so the pod must rejoin rotation the moment a probe may flow."""
        if self._draining:
            return False, "draining"
        if self._engine is not None:
            if not self._engine.loop_alive():
                return False, "engine loop dead (watchdog reviving)"
            if self._breaker is not None and self._breaker.state() == "open":
                return False, "circuit breaker open"
        return True, "ok"

    def drain_status(self) -> dict:
        """The scale-down probe (GET /debug/drain): is this replica
        draining, how much HTTP work is still in flight, and how many
        session chains it still tracks. The autoscaler polls this
        between "release every session" and "kill the replica" so the
        kill lands on an idle process whose chains are parked
        (docs/AUTOSCALING.md drain timeline)."""
        doc = {
            "instance": self.instance,
            "draining": self._draining,
            "active_http_requests": self.active_http_requests(),
            "sessions_tracked": 0,
            "tier_spilled_bytes": 0,
        }
        if self._engine is not None and self._engine.paged:
            e = self._engine.stats()
            doc["sessions_tracked"] = e.get("sessions_tracked", 0)
            doc["tier_spilled_bytes"] = e.get("tier_spilled_bytes", 0)
        return doc

    def http_begin(self) -> None:
        with self._stats_lock:
            self._active_http += 1

    def http_end(self) -> None:
        with self._stats_lock:
            self._active_http -= 1

    def active_http_requests(self) -> int:
        with self._stats_lock:
            return self._active_http

    def _adapter_id(self, adapter: "str | None") -> int:
        """Adapter name -> MultiLoraDense slot. None/'base' is slot 0
        (the base model, valid whether or not adapters are loaded);
        anything else must name a loaded adapter."""
        if adapter is None or adapter == "base":
            return 0
        if self.adapter_names is None:
            raise ValueError(
                f"adapter {adapter!r} requested but no adapters are "
                f"loaded (--lora-adapters)")
        try:
            return self.adapter_names.index(adapter) + 1
        except ValueError:
            raise ValueError(
                f"unknown adapter {adapter!r}; available: "
                f"{['base'] + self.adapter_names}")

    def _validate_gen(self, prompts, max_new_tokens, num_samples):
        """Shared eager validation for generate_tokens/generate_stream —
        ONE copy, so a new rule (or a changed bound) applies to the
        streaming and non-streaming routes alike. Returns the coerced
        (max_new_tokens, num_samples)."""
        if not self.model_name.startswith(("transformer", "moe")):
            raise ValueError(f"{self.model_name} is not a generative LM")
        if not prompts or any(len(p) == 0 for p in prompts):
            raise ValueError("prompts must be non-empty token lists")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        num_samples = int(num_samples)
        if num_samples < 1:
            raise ValueError("num_samples must be >= 1")
        # EVERY route honors the served maximum — the engine would happily
        # chunk an unbounded request into hours of work otherwise.
        served_batch(len(prompts) * num_samples)
        return max_new_tokens, num_samples

    def _sanitize_gen(self, lens: "list[int]", max_new_tokens: int,
                      temperature: float, top_k: "int | None",
                      top_p: "float | None", eos_id: "int | None"):
        """Everything that reaches generate()/the engine as a STATIC jit
        argument is bucketed/quantized here, so a hostile or chatty client
        can only ever populate a small fixed set of compiled programs
        (same reasoning as the BATCH_SIZES padding for predict()). ONE
        policy shared by generate_tokens and generate_stream — the width
        bucket is also the engine's admission unit (serve/programs.py),
        so validation here == acceptance there."""
        from k3stpu.serve.programs import prompt_width_bucket

        width = prompt_width_bucket(max(lens), self.seq_len)
        if max(lens) > width:
            raise ValueError(
                f"prompt length {max(lens)} exceeds max seq {width}")
        if width + max_new_tokens > self.seq_len:
            raise ValueError(
                f"prompt width {width} + max_new_tokens {max_new_tokens} "
                f"exceeds the KV cache ({self.seq_len}); lower one of them")
        gen_budget = 1 << (max_new_tokens - 1).bit_length()  # pow2 bucket
        gen_budget = min(gen_budget, self.seq_len - width)
        vocab = lm_base_cfg(self.model.config).vocab_size
        temperature = round(max(0.0, min(float(temperature), 4.0)), 1)
        if top_p is not None:  # 0.1 bucket: top_p is STATIC in generate()
            top_p = round(max(0.05, min(float(top_p), 1.0)), 1)
            if top_p >= 1.0:
                top_p = None  # 1.0 == no cut; keep one compiled program
        if top_k is not None:  # pow2 bucket, capped at the vocab
            top_k = min(1 << (max(1, int(top_k)) - 1).bit_length(), vocab)
        if eos_id is not None:  # traced in generate(), so any value is one
            eos_id = int(eos_id)  # program — just validate the range
            if not 0 <= eos_id < vocab:
                raise ValueError(f"eos_id {eos_id} outside vocab [0, {vocab})")
        return width, gen_budget, temperature, top_k, top_p, eos_id

    def _corrupt_check(self, rows: "list[list[int]]") -> "list[list[int]]":
        """Chaos point ``gen_corrupt``: when armed, perturb every output
        token (+1 mod vocab) while the request completes normally — the
        silent-wrong-output failure mode (miscompile, corrupt tier
        restore, bad TP re-split) that looks healthy on every latency
        gauge and that only the canary's token-exact compare catches."""
        if self._chaos is None:
            return rows
        from k3stpu.chaos import InjectedFault
        try:
            self._chaos.fire("gen_corrupt")
        except InjectedFault:
            vocab = lm_base_cfg(self.model.config).vocab_size
            return [[(int(t) + 1) % vocab for t in row] for row in rows]
        return rows

    def generate_tokens(self, prompts: "list[list[int]]",
                        max_new_tokens: int = 32, temperature: float = 0.0,
                        top_k: "int | None" = None,
                        top_p: "float | None" = None,
                        eos_id: "int | None" = None,
                        num_samples: int = 1,
                        adapter: "str | None" = None,
                        trace_id: "str | None" = None,
                        session: "str | None" = None,
                        synthetic: bool = False,
                        priority: str = "interactive",
                        deadline_ms: "float | None" = None) \
            -> "list[list[int]]":
        """KV-cache generation for a ragged batch of token prompts.

        Prompts are right-padded with each row's last token to a shared
        power-of-two width, and the batch to the next served batch size —
        both keep the jitted prefill/decode programs to a small fixed set
        (models/generate.py handles the ragged lengths exactly).

        ``num_samples > 1`` (single prompt only) returns n sampled
        continuations; under the continuous-batching engine the prompt
        prefills ONCE and fans out across slots (shared-prefix sampling),
        otherwise it expands to n batch rows.
        """
        import jax.numpy as jnp

        from k3stpu.models.generate import generate

        max_new_tokens, num_samples = self._validate_gen(
            prompts, max_new_tokens, num_samples)
        aid = self._adapter_id(adapter)
        self._validate_session(session, prompts, num_samples)
        timeout_s = self._deadline_timeout(deadline_ms)
        if num_samples > 1:
            if len(prompts) != 1:
                raise ValueError(
                    "num_samples > 1 takes exactly one prompt")
            if self._engine is None:
                # No engine: expand to n batch rows (n prefills of the
                # same prompt — correct, without the shared-prefix
                # saving). The engine route happens AFTER the shared
                # sanitization block below.
                prompts = prompts * num_samples
                num_samples = 1

        lens = [len(p) for p in prompts]
        (width, gen_budget, temperature, top_k, top_p,
         eos_id) = self._sanitize_gen(lens, max_new_tokens, temperature,
                                      top_k, top_p, eos_id)

        if num_samples > 1:  # engine-backed shared-prefix sampling
            t0 = time.perf_counter()
            out = []
            # ONE admission token for the whole request: re-gating each
            # slot-sized chunk would reject an admitted request mid-
            # flight after burning its earlier chunks' decode work.
            self._engine.take_admission_token()
            try:
                for ofs in range(0, num_samples, self._engine.slots):
                    k = min(self._engine.slots, num_samples - ofs)
                    out.extend(self._engine.submit_samples(
                        prompts[0], k, max_new_tokens=gen_budget,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        eos_id=eos_id, adapter_id=aid, admitted=True,
                        trace_id=trace_id, synthetic=synthetic,
                        timeout_s=timeout_s, priority=priority))
            finally:
                self._engine.release_admission_token()
            dt = time.perf_counter() - t0
            out = [row[:max_new_tokens] for row in out]
            with self._stats_lock:
                self._stats["gen_requests"] += 1
                self._stats["gen_examples"] += num_samples
                self._stats["tokens"] += sum(len(r) for r in out)
                self._stats["gen_seconds"] += dt
            return self._corrupt_check(out)

        # Spec decode needs a gamma-token margin in the cache; requests
        # without it (or sampled / adapter-routed ones — the draft model
        # has no adapter stacks to draft with) take the plain path.
        if aid == 0 and self._spec_eligible(width, gen_budget, temperature):
            from k3stpu.serve.speculative import speculative_generate

            # Same bounded-compile-cache discipline as every other route:
            # the batch pads to a served bucket (and oversize requests are
            # rejected), so spec programs compile per bucket, not per n.
            n = len(prompts)
            batch = served_batch(n)
            block = np.zeros((batch, width), np.int32)
            for i, p in enumerate(prompts):
                block[i, :len(p)] = p
            block[n:] = block[n - 1]
            plens = np.asarray(lens + [lens[-1]] * (batch - n), np.int32)
            t0 = time.perf_counter()
            with self._lock:
                out, spec = speculative_generate(
                    self.model, self._variables["params"],
                    self._draft[0], self._draft[1], block,
                    plens, gen_budget,
                    gamma=self.spec_gamma)
            out = out[:n]
            dt = time.perf_counter() - t0
            out = out[:, :max_new_tokens]
            if eos_id is not None:
                # Greedy spec emits the target's tokens; apply the same
                # eos-latch semantics as the plain path post hoc.
                out = out.copy()
                for r in range(n):
                    hits = np.nonzero(out[r] == eos_id)[0]
                    if hits.size:
                        out[r, hits[0]:] = eos_id
            with self._stats_lock:
                self._stats["gen_requests"] += 1
                self._stats["gen_examples"] += n
                self._stats["tokens"] += int(out.size)
                self._stats["gen_seconds"] += dt
                self._spec_stats["requests"] += 1
                self._spec_stats["proposed"] += spec["proposed"]
                self._spec_stats["accepted"] += spec["accepted"]
            # Engine-less path: the server IS the request lifecycle, so
            # e2e is observed here (engine paths record inside the loop).
            # Synthetic (canary) probes stay out of the organic families.
            if synthetic:
                self._obs.synthetic_requests.inc()
            else:
                self._obs.e2e.observe(dt, trace_id=trace_id)
            return self._corrupt_check(out.tolist())

        if self._engine is not None:
            # Continuous batching: no global lock — the engine interleaves
            # this request with whatever is already decoding. Requests
            # wider than the slot block split into slot-sized chunks (the
            # engine interleaves those too; BATCH_SIZES[-1] stays the
            # served maximum either way).
            t0 = time.perf_counter()
            out = []
            # ONE admission token per HTTP request (see the samples path).
            self._engine.take_admission_token()
            try:
                for ofs in range(0, len(prompts), self._engine.slots):
                    out.extend(self._engine.submit(
                        prompts[ofs:ofs + self._engine.slots],
                        max_new_tokens=gen_budget, temperature=temperature,
                        top_k=top_k, top_p=top_p, eos_id=eos_id,
                        adapter_id=aid, admitted=True, trace_id=trace_id,
                        session=session, synthetic=synthetic,
                        timeout_s=timeout_s, priority=priority))
            finally:
                self._engine.release_admission_token()
            dt = time.perf_counter() - t0
            out = [row[:max_new_tokens] for row in out]
            with self._stats_lock:
                self._stats["gen_requests"] += 1
                self._stats["gen_examples"] += len(prompts)
                self._stats["tokens"] += sum(len(r) for r in out)
                self._stats["gen_seconds"] += dt
            return self._corrupt_check(out)

        n = len(prompts)
        batch = served_batch(n)

        block = np.zeros((batch, width), np.int32)
        for i, p in enumerate(prompts):
            block[i, :len(p)] = p
            block[i, len(p):] = p[-1]  # pad with the row's last real token
        block[n:] = block[n - 1 if n else 0]  # batch padding rows
        plens = np.array(lens + [lens[-1]] * (batch - n), np.int32)

        import jax

        t0 = time.perf_counter()
        with self._lock:
            # Fresh key per request (traced arg — no recompile): sampled
            # continuations differ across requests but stay reproducible
            # for a given request ordinal.
            self._gen_counter += 1
            rng = jax.random.key(self._gen_counter)
            akw = ({"adapter_ids": jnp.full((batch,), aid, jnp.int32)}
                   if getattr(lm_base_cfg(self.model.config),
                              "multi_lora", None)
                   else {})
            out = np.asarray(generate(
                self.model, self._variables["params"], jnp.asarray(block),
                jnp.asarray(plens), gen_budget, rng=rng,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, **akw))
        dt = time.perf_counter() - t0
        out = out[:n, :max_new_tokens]
        with self._stats_lock:
            self._stats["gen_requests"] += 1
            self._stats["gen_examples"] += n
            self._stats["tokens"] += int(out.size)
            self._stats["gen_seconds"] += dt
        # engine-less: see the spec path note
        if synthetic:
            self._obs.synthetic_requests.inc()
        else:
            self._obs.e2e.observe(dt, trace_id=trace_id)
        return self._corrupt_check(out.tolist())

    @staticmethod
    def _deadline_timeout(deadline_ms: "float | None") -> float:
        """Map a client ``deadline_ms`` onto the engine's submit timeout:
        a request that cannot finish inside its deadline should fail AT
        the deadline (EngineStalled -> 503 + Retry-After), not hold its
        slot for the default ten minutes. Capped at the default so a huge
        deadline never extends the watchdog window."""
        if deadline_ms is None:
            return 600.0
        d = float(deadline_ms)
        if not (d > 0.0) or d != d:
            raise ValueError(
                f"deadline_ms must be a positive number, got {deadline_ms!r}")
        return min(600.0, d / 1000.0)

    def _validate_session(self, session, prompts, num_samples) -> None:
        """ONE gate for the session-id API, shared by generate_tokens
        and generate_stream: sessions name exactly one paged KV chain,
        so they need the paged engine and a single unsampled prompt."""
        if session is None:
            return
        if not isinstance(session, str) or not session:
            raise ValueError("session must be a non-empty string")
        if self._engine is None or not self._engine.paged:
            raise ValueError(
                "session ids require --continuous-batching with "
                "--kv-page-size (the chain a session names lives in "
                "the page pool)")
        if len(prompts) != 1 or num_samples != 1:
            raise ValueError("session takes exactly one prompt and "
                             "num_samples == 1 (a session names ONE "
                             "chain)")

    def _spec_eligible(self, width: int, gen_budget: int,
                       temperature: float) -> bool:
        """ONE routing gate for speculative decode, shared by
        generate_tokens and generate_stream — the same request must route
        identically with and without "stream": true, or the final stream
        frame stops matching the non-streaming response."""
        return (self._draft is not None and temperature == 0.0
                and width + gen_budget + self.spec_gamma + 1
                <= self.seq_len)

    def generate_stream(self, prompts: "list[list[int]]",
                        max_new_tokens: int = 32, temperature: float = 0.0,
                        top_k: "int | None" = None,
                        top_p: "float | None" = None,
                        eos_id: "int | None" = None,
                        num_samples: int = 1,
                        adapter: "str | None" = None,
                        trace_id: "str | None" = None,
                        session: "str | None" = None,
                        synthetic: bool = False,
                        priority: str = "interactive",
                        deadline_ms: "float | None" = None):
        """Streaming generate: an iterator of JSON-able events for the
        SSE route. Engine-backed requests yield per-decode-block deltas
        ``{"done": False, "rows": {global_row: [tok, ...]}}`` as tokens
        decode (time-to-first-token = prefill latency, not full-budget
        latency), then a final ``{"done": True, "tokens": [[...]]}``
        identical to generate_tokens()'s return. Paths with no
        incremental results — no engine, ``num_samples > 1``, the
        speculative-decode route — degrade to the single final event.

        Validation runs EAGERLY (this is not a generator function), so
        bad arguments raise here and become a clean 400; only transport
        of an already-admitted request can fail mid-stream."""
        max_new_tokens, num_samples = self._validate_gen(
            prompts, max_new_tokens, num_samples)
        aid = self._adapter_id(adapter)
        self._validate_session(session, prompts, num_samples)
        timeout_s = self._deadline_timeout(deadline_ms)
        lens = [len(p) for p in prompts]
        (width, gen_budget, temperature, top_k, top_p,
         eos_id) = self._sanitize_gen(lens, max_new_tokens, temperature,
                                      top_k, top_p, eos_id)
        spec_route = (num_samples == 1 and aid == 0 and
                      self._spec_eligible(width, gen_budget, temperature))
        if self._engine is None or num_samples > 1 or spec_route:
            tokens = self.generate_tokens(
                prompts, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, num_samples=num_samples, adapter=adapter,
                trace_id=trace_id, synthetic=synthetic,
                priority=priority, deadline_ms=deadline_ms)
            return iter([{"done": True, "tokens": tokens}])
        # Engine route only, AFTER the routing decisions (a spec/fallback
        # request never touches the admission counter, so it must not be
        # shed by it). The advisory check turns an overload into a clean
        # pre-header 503; the AUTHORITATIVE token take happens inside
        # the generator on first next() — taking it here would leak the
        # max_pending slot whenever the generator is never started
        # (close() on a never-started generator skips its finally, e.g.
        # after a header-write failure in _send_sse). The advisory/take
        # race window means a take can still fail mid-stream, which
        # degrades to an SSE error frame rather than a 503.
        self._engine.reject_if_at_capacity()
        return self._stream_engine_events(
            prompts, max_new_tokens, gen_budget, temperature, top_k,
            top_p, eos_id, aid, trace_id, session, synthetic,
            priority, timeout_s)

    def _stream_engine_events(self, prompts, max_new_tokens, gen_budget,
                              temperature, top_k, top_p, eos_id, aid=0,
                              trace_id=None, session=None,
                              synthetic=False, priority="interactive",
                              timeout_s=600.0):
        """Engine-backed streaming (args pre-sanitized). The admission
        token is taken HERE, on the generator's first next(), so a
        generator that is created but never iterated cannot leak the
        slot; the matching release is in the finally, which is
        guaranteed to run once the generator has started. Requests wider
        than the slot block stream chunk by chunk with global row
        indices; deltas clip at max_new_tokens per row (the engine
        decodes the pow2 gen_budget — surplus never reaches the client,
        matching the non-streaming truncation)."""
        t0 = time.perf_counter()
        out: "list[list[int]]" = []
        self._engine.take_admission_token()
        try:
            yield from self._stream_engine_chunks(
                prompts, max_new_tokens, gen_budget, temperature, top_k,
                top_p, eos_id, aid, out, trace_id, session, synthetic,
                priority, timeout_s)
        finally:
            self._engine.release_admission_token()
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["gen_requests"] += 1
            self._stats["gen_examples"] += len(prompts)
            self._stats["tokens"] += sum(len(r) for r in out)
            self._stats["gen_seconds"] += dt
        yield {"done": True, "tokens": self._corrupt_check(out)}

    def _stream_engine_chunks(self, prompts, max_new_tokens, gen_budget,
                              temperature, top_k, top_p, eos_id, aid,
                              out, trace_id=None, session=None,
                              synthetic=False, priority="interactive",
                              timeout_s=600.0):
        for ofs in range(0, len(prompts), self._engine.slots):
            chunk = prompts[ofs:ofs + self._engine.slots]
            emitted = [0] * len(chunk)
            events = self._engine.submit_stream(
                chunk, max_new_tokens=gen_budget,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_id=eos_id, adapter_id=aid, admitted=True,
                trace_id=trace_id, session=session, synthetic=synthetic,
                priority=priority, timeout_s=timeout_s)
            try:
                for ev in events:
                    if ev["done"]:
                        out.extend(row[:max_new_tokens]
                                   for row in ev["tokens"])
                        continue
                    rows = {}
                    for j, toks in ev["rows"].items():
                        take = toks[:max_new_tokens - emitted[j]]
                        if take:
                            emitted[j] += len(take)
                            rows[ofs + j] = take
                    if rows:
                        yield {"done": False, "rows": rows}
            finally:
                # Deterministic teardown: if THIS generator is closed
                # (client disconnect) or errors, closing the engine
                # stream fires its cancel path — the engine expires the
                # request instead of decoding on for nobody. No-op when
                # the stream ran to completion.
                events.close()

    def release_session(self, session: str, spill: bool = False) -> bool:
        """Park a session's cached KV chain between turns: the chain
        leaves the device pool for the host tier (--tier-host-mb) or is
        dropped (no tier), and its HBM pages return to admission. The
        POST /v1/session/release body. ``spill`` forces the parked
        chain through to the disk tier (--tier-dir) so it survives
        this process — the autoscaler's drain-before-kill path.
        Returns whether the session named a live chain."""
        if not isinstance(session, str) or not session:
            raise ValueError("session must be a non-empty string")
        if self._engine is None or not self._engine.paged:
            raise ValueError(
                "session release requires --continuous-batching with "
                "--kv-page-size")
        return self._engine.release_session(session, spill=spill)

    # --- disaggregated prefill/decode (docs/DISAGG.md) ------------------

    def export_kv(self, prompt_tokens: "list[int]",
                  adapter: "str | None" = None) -> bytes:
        """The POST /v1/prefill body of a prefill-role replica: run (or
        reuse) the prompt's prefill and return the finished KV page
        chain in the checksummed HostPageStore wire format, ready for a
        decode peer's import_chain. Served by any paged replica — the
        role gate is placement policy (the router only routes prefill
        work at prefill-role replicas), not a capability gate, which
        keeps single-process tests honest."""
        if self._engine is None or not self._engine.paged:
            raise ValueError(
                "/v1/prefill requires --continuous-batching with "
                "--kv-page-size")
        if not isinstance(prompt_tokens, list) or not prompt_tokens:
            raise ValueError("prompt_tokens must be a non-empty token list")
        aid = self._adapter_id(adapter)
        return self._engine.export_chain(
            [int(t) for t in prompt_tokens], adapter_id=aid)

    def maybe_disagg_prefetch(self, prompts, adapter: "str | None",
                              endpoint: "str | None") -> None:
        """Decode-role fast path, called by the HTTP layer before a
        generate request is admitted: pull the prompt's KV chain from
        the prefill peer (the router's X-K3STPU-Prefill-Endpoint header,
        falling back to --prefill-upstream) and install it in the
        prompt cache, so admission lands as an exact hit and the decode
        loop never runs this prompt's prefill. Strictly best-effort:
        ANY failure — peer down, torn stream, checksum mismatch, pool
        too tight — counts a transfer fallback and the request proceeds
        through the normal cold-prefill path with identical output."""
        if self.role != "decode" or self._engine is None:
            return
        if not (isinstance(prompts, list) and len(prompts) == 1
                and isinstance(prompts[0], list) and prompts[0]):
            return  # multi-prompt batches take the normal path
        endpoint = endpoint or self._prefill_upstream
        if not endpoint:
            return
        import urllib.request

        body = json.dumps({"prompt_tokens": [int(t) for t in prompts[0]],
                           "adapter": adapter}).encode()
        try:
            req = urllib.request.Request(
                endpoint.rstrip("/") + "/v1/prefill", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=self._prefill_timeout_s) as resp:
                data = resp.read()
            # import_chain counts its own fallback when the payload is
            # torn or the pool can't host the chain.
            self._engine.import_chain(data)
        except Exception:
            self._engine.note_transfer_fallback()

    def busy_seconds(self) -> float:
        """Cumulative device-busy time — the duty-cycle numerator the
        telemetry thread differentiates. With an engine, generate busy
        time is the LOOP's measured dispatch time (gen_seconds is
        per-request wall time there: concurrent requests overlap on the
        one chip and would double-count the same busy second)."""
        with self._stats_lock:
            seconds = self._stats["seconds"]
            gen = self._stats["gen_seconds"]
        if self._engine is not None:
            gen = self._engine.stats()["busy_s"]
        return seconds + gen

    @staticmethod
    def _lora_rank_in(meta_tree) -> "int | None":
        """Rank of the first lora_a leaf in a checkpoint metadata tree
        (None when the checkpoint carries no adapters)."""
        if isinstance(meta_tree, dict):
            a = meta_tree.get("lora_a")
            if a is not None and hasattr(a, "shape"):
                return int(a.shape[-1])
            for v in meta_tree.values():
                r = InferenceServer._lora_rank_in(v)
                if r is not None:
                    return r
        return None

    @staticmethod
    def _emit(lines: list, name: str, mtype: str, help_text: str,
              value) -> None:
        lines += [f"# HELP {name} {help_text}",
                  f"# TYPE {name} {mtype}",
                  f"{name} {value}"]

    def prometheus_metrics(self) -> str:
        """Prometheus text exposition of the live counters plus the obs
        layer's latency histograms/gauges — the K8s-native scrape
        surface (a ServiceMonitor against the Service port replaces
        reading /v1/models by hand). Counters and distributions only;
        rates and quantiles are the scraper's job."""
        return (self._counter_exposition()
                + self._obs.render_prometheus() + "\n")

    def _counter_exposition(self) -> str:
        """The hand-rendered (non-obs) counter/gauge families, shared by
        the plain and OpenMetrics render paths."""
        with self._stats_lock:
            s = dict(self._stats)
        lines: "list[str]" = []
        emit = self._emit
        emit(lines, "k3stpu_predict_requests_total", "counter",
             "Predict requests served.", s["requests"])
        emit(lines, "k3stpu_predict_examples_total", "counter",
             "Predict examples (rows) served.", s["examples"])
        emit(lines, "k3stpu_predict_dispatches_total", "counter",
             "Device dispatches for predict (coalesced batches).",
             s["dispatches"])
        emit(lines, "k3stpu_predict_device_seconds_total", "counter",
             "Device-busy seconds spent on predict.",
             f"{s['seconds']:.6f}")
        emit(lines, "k3stpu_generate_requests_total", "counter",
             "Generate requests served.", s["gen_requests"])
        emit(lines, "k3stpu_generate_tokens_total", "counter",
             "Tokens produced by generate.", s["tokens"])
        emit(lines, "k3stpu_generate_device_seconds_total", "counter",
             "Wall seconds spent in generate calls.",
             f"{s['gen_seconds']:.6f}")
        if self._engine is not None:
            e = self._engine.stats()
            emit(lines, "k3stpu_engine_decode_steps_total", "counter",
                 "Engine decode steps (one token per active row).",
                 e["steps"])
            emit(lines, "k3stpu_engine_dispatches_total", "counter",
                 "Engine device round-trips (decode_block steps each).",
                 e["dispatches"])
            emit(lines, "k3stpu_engine_tokens_total", "counter",
                 "Tokens produced by the engine.", e["tokens"])
            emit(lines, "k3stpu_engine_busy_seconds_total", "counter",
                 "Engine loop device-busy seconds.",
                 f"{e['busy_s']:.6f}")
            if self._engine.max_pending is not None:
                emit(lines, "k3stpu_engine_rejected_total", "counter",
                     "Requests shed at admission (backpressure 503s).",
                     e["rejected"])
            if self._engine.prompt_cache > 0:
                emit(lines, "k3stpu_pcache_hits_total", "counter",
                     "Prompt-cache exact hits (prefill skipped).",
                     e["pcache_hits"])
                emit(lines, "k3stpu_pcache_prefix_hits_total", "counter",
                     "Prompt-cache prefix hits (suffix-only prefill).",
                     e["pcache_prefix_hits"])
                emit(lines, "k3stpu_pcache_misses_total", "counter",
                     "Prompt-cache misses (full prefill).",
                     e["pcache_misses"])
                emit(lines, "k3stpu_pcache_bytes", "gauge",
                     "HBM held by prompt-cache entries.",
                     e["pcache_bytes"])
            if self._engine.paged:
                emit(lines, "k3stpu_pages_total", "gauge",
                     "Allocatable KV pages in the pool.",
                     e["pages_total"])
                emit(lines, "k3stpu_pages_free", "gauge",
                     "KV pages currently free.", e["pages_free"])
                emit(lines, "k3stpu_pages_pinned", "gauge",
                     "KV pages pinned by prompt-cache entries.",
                     e["pages_pinned"])
                emit(lines, "k3stpu_page_utilization", "gauge",
                     "Fraction of the page pool in use.",
                     e["page_utilization"])
                emit(lines, "k3stpu_pcache_shared_pages", "gauge",
                     "Pinned pages with more than one reference.",
                     e["pcache_shared_pages"])
                emit(lines, "k3stpu_paged_density_ratio", "gauge",
                     "Dense token-slots per actual pooled token-slot.",
                     e["paged_density_ratio"])
            if self._tier is not None and self._engine.paged:
                # Tier swap latencies + hit/miss/fallback counters and
                # the pages_resident/host_tier_pages gauges render from
                # the shared obs layer; these are the capacity-ledger
                # extras only the engine's stats dict carries.
                emit(lines, "k3stpu_tier_entries", "gauge",
                     "Chains (pcache keys) held by the host tier.",
                     e["tier_entries"])
                emit(lines, "k3stpu_tier_host_bytes", "gauge",
                     "Host RAM held by resident tier chains.",
                     e["tier_bytes"])
                emit(lines, "k3stpu_tier_spilled_bytes", "gauge",
                     "Bytes of tier chains spilled to the disk tier.",
                     e["tier_spilled_bytes"])
                emit(lines, "k3stpu_tier_sessions", "gauge",
                     "Session ids with a tracked chain (device or "
                     "host).", e["sessions_tracked"])
                emit(lines, "k3stpu_tier_swap_ins_total", "counter",
                     "Chains restored from the host tier into fresh "
                     "device pages.", e["tier_swap_ins"])
                emit(lines, "k3stpu_tier_swap_outs_total", "counter",
                     "Chains gathered off-device into the host tier.",
                     e["tier_swap_outs"])
            if self.role != "monolithic":
                # Disagg handoff ledger (docs/DISAGG.md). Transfer
                # latency, wire bytes, and fallback counts render from
                # the shared obs layer; these are the engine's
                # completed-handoff totals per direction. Gated on role
                # so a monolithic replica's exposition stays byte-stable.
                emit(lines, "k3stpu_kv_exports_total", "counter",
                     "KV page chains serialized for a decode peer "
                     "(/v1/prefill responses).", e["kv_exports"])
                emit(lines, "k3stpu_kv_imports_total", "counter",
                     "KV page chains restored from a prefill peer.",
                     e["kv_imports"])
            # Containment counters (docs/RESILIENCE.md).
            emit(lines, "k3stpu_engine_deadline_expired_total", "counter",
                 "Requests reaped by the deadline machinery (client "
                 "timeout, disconnect, or watchdog expiry).",
                 e["deadline_expired"])
            emit(lines, "k3stpu_engine_watchdog_trips_total", "counter",
                 "Watchdog trips: engine-loop stalls that failed blocked "
                 "clients with retryable errors.",
                 e["watchdog_trips"])
            emit(lines, "k3stpu_engine_loop_crashes_total", "counter",
                 "Crash-only engine resets after an unexpected dispatch "
                 "failure.", e["loop_crashes"])
            emit(lines, "k3stpu_engine_loop_restarts_total", "counter",
                 "Engine loop threads revived by the watchdog after "
                 "dying.", e["loop_restarts"])
            if self._breaker is not None:
                emit(lines, "k3stpu_breaker_state", "gauge",
                     "Circuit breaker state: 0 closed, 1 half-open, "
                     "2 open.", self._breaker.state_value())
                emit(lines, "k3stpu_breaker_trips_total", "counter",
                     "Circuit breaker transitions to open.",
                     self._breaker.trips)
                emit(lines, "k3stpu_breaker_rejected_total", "counter",
                     "Requests rejected at admission while the breaker "
                     "was open.", e["breaker_rejected"])
        if self._draft is not None:
            with self._stats_lock:
                sp = dict(self._spec_stats)
            emit(lines, "k3stpu_spec_proposed_total", "counter",
                 "Draft tokens proposed by speculative decode.",
                 sp["proposed"])
            emit(lines, "k3stpu_spec_accepted_total", "counter",
                 "Draft tokens accepted by the target model.",
                 sp["accepted"])
        return "\n".join(lines) + "\n"

    def openmetrics(self) -> str:
        """OpenMetrics exposition of the same families, served when the
        scraper content-negotiates for it (Accept:
        application/openmetrics-text). The extra value over the plain
        format: histogram bucket lines carry trace-id exemplars, so a
        latency spike links straight to its request trace. The default
        (no Accept header) scrape keeps the plain text/plain format
        byte-for-byte — old scrapers never see exemplar syntax."""
        return (prometheus_text_to_openmetrics(self._counter_exposition())
                + self._obs.render_openmetrics() + "\n# EOF\n")

    def debug_timelines(self, n: int = 50) -> dict:
        """Last n request timelines (completed ring + live), newest
        last — the GET /debug/requests payload. Carries the active
        attention backend so traces attribute decode latency to the
        kernel that produced it."""
        return {"requests": self._obs.timelines(n),
                "attn_backend": self.attn_backend}

    def debug_trace(self) -> dict:
        """Chrome-trace-format export of the request ring — the GET
        /debug/trace payload; save as .json and open in
        ui.perfetto.dev or chrome://tracing."""
        return self._obs.chrome_trace()

    def debug_profile(self, seconds: float) -> str:
        """On-demand jax.profiler capture around whatever the process is
        dispatching (the engine loop keeps running — that's the point:
        the capture sees live decode steps, not a synthetic workload).
        Returns the trace directory; open it with tensorboard's profile
        plugin or xprof. One capture at a time; seconds is clamped so a
        fat-fingered request can't pin the handler thread for minutes."""
        import tempfile

        import jax

        seconds = min(max(float(seconds), 0.1), 60.0)
        if not self._profile_lock.acquire(blocking=False):
            raise RuntimeError("a profile capture is already running")
        try:
            out = tempfile.mkdtemp(prefix="k3stpu-profile-")
            jax.profiler.start_trace(out)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            return out
        finally:
            self._profile_lock.release()

    def _spec_card(self) -> "dict | None":
        if self._draft is None:
            return None
        with self._stats_lock:
            s = dict(self._spec_stats)
        s["gamma"] = self.spec_gamma
        s["acceptance_rate"] = (round(s["accepted"] / s["proposed"], 4)
                                if s["proposed"] else None)
        return s

    def _quant_card(self) -> "dict | None":
        if self.quant is None and self.kv_cache_dtype is None:
            return None
        card = {"kv_cache_dtype": self.kv_cache_dtype}
        if self.quant is not None:
            # Weight-quant fields only when weights ARE quantized — a
            # kv-only card must not read as a broken weight-quant state.
            from k3stpu.models.quant import param_bytes

            card.update(
                mode=self.quant,
                param_bytes=param_bytes(self._variables["params"]),
                float_param_bytes=self.float_param_bytes)
        return card

    def model_card(self) -> dict:
        import jax

        with self._stats_lock:
            stats = dict(self._stats)
        # Throughput over device-busy time (the chip's achieved rate; wall
        # time would also bill idle periods between requests), plus the
        # average coalesced batch — the micro-batching win, observable.
        throughput = {
            "examples_per_s": (round(stats["examples"] / stats["seconds"], 2)
                               if stats["seconds"] > 0 else None),
            "tokens_per_s": (round(stats["tokens"] / stats["gen_seconds"], 2)
                             if stats["gen_seconds"] > 0 else None),
            "avg_examples_per_dispatch": (
                round(stats["examples"] / stats["dispatches"], 2)
                if stats["dispatches"] else None),
        }
        return {
            "model": self.model_name,
            "role": self.role,
            "input_shape": list(self.input_shape()),
            "input_dtype": np.dtype(self.input_dtype()).name,
            "batch_sizes": list(BATCH_SIZES),
            "batching": {"window_ms": (self._batcher._window_s * 1e3
                                       if self._batcher else 0.0)},
            "sharding": (dict(self._mesh.shape) if self._mesh else None),
            "tp_shards": self.tp_shards,
            "adapters": (["base"] + self.adapter_names
                         if self.adapter_names else None),
            "quant": self._quant_card(),
            "engine": (self._engine.stats() if self._engine else None),
            "speculative": self._spec_card(),
            "checkpoint_step": self.loaded_step,
            "devices": [str(d) for d in jax.devices()],
            "stats": stats,
            "throughput": throughput,
        }


def make_app(server: InferenceServer):
    """Returns the BaseHTTPRequestHandler class bound to `server`."""
    from k3stpu.serve.containment import CircuitOpen, EngineStalled
    from k3stpu.serve.engine import AdmissionRejected, EngineOverloaded

    class Handler(BaseHTTPRequestHandler):
        # W3C trace context for the CURRENT request: (trace_id,
        # parent_span_id | None). Set per request at the top of do_POST;
        # the class default keeps GET paths (which never set it) safe.
        _trace_ctx: "tuple[str, str | None] | None" = None

        def _begin_trace(self) -> None:
            """Accept the inbound traceparent or mint a fresh identity.
            parse_traceparent is a strict allow-list: malformed or
            oversized headers yield None and the request proceeds under
            a new id — raw header bytes never travel further than this
            line."""
            parsed = parse_traceparent(self.headers.get("traceparent"))
            self._trace_ctx = parsed if parsed is not None \
                else (new_trace_id(), None)

        def _trace_id(self) -> "str | None":
            return self._trace_ctx[0] if self._trace_ctx else None

        def _trace_headers(self) -> None:
            """Echo the request's trace id (with a server-minted span id)
            on the in-flight response — EVERY response, 503s and
            timeouts included, so a shed or failed request is still
            joinable against /debug/trace and the client's own log."""
            if self._trace_ctx is not None:
                self.send_header("traceparent", format_traceparent(
                    self._trace_ctx[0], new_span_id()))

        def _send(self, code: int, payload: dict,
                  headers: "dict | None" = None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            # Replica identity on EVERY response (503s included): the
            # router's failover accounting and loadgen's per-replica
            # report both read it.
            self.send_header("X-K3STPU-Replica", server.instance)
            self._trace_headers()
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet; stats live in /v1/models
            pass

        def _send_sse(self, events):
            """Server-sent events: one ``data: {json}`` frame per event,
            flushed as produced (the client's read unblocks on each
            decode block — this is the whole point). HTTP/1.0 + an
            explicit Connection: close delimit the stream by EOF; no
            Content-Length. Mid-stream failures (the request was already
            admitted, so no 4xx is possible) become a final
            ``{"error": ...}`` frame."""
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.send_header("X-K3STPU-Replica", server.instance)
            self._trace_headers()
            self.end_headers()
            chaos = server._chaos
            try:
                for ev in events:
                    if chaos is not None:
                        # "sse_write" raising BrokenPipeError simulates a
                        # client disconnect mid-stream (chaos suite).
                        chaos.fire("sse_write")
                    self.wfile.write(
                        b"data: " + json.dumps(ev).encode() + b"\n\n")
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                # Client went away mid-stream: close the event generator,
                # which cancels the underlying engine request (its slots
                # free next loop iteration) instead of letting it decode
                # its whole budget for nobody. (The no-engine fallback
                # returns a plain list iterator — nothing to close.)
                getattr(events, "close", lambda: None)()
            except Exception as e:  # noqa: BLE001 — headers already sent
                getattr(events, "close", lambda: None)()
                try:
                    self.wfile.write(
                        b"data: "
                        + json.dumps({"error": str(e)}).encode() + b"\n\n")
                except OSError:
                    pass

        def do_GET(self):
            if self.path == "/healthz":
                # READINESS: not-ready while draining, while the engine
                # loop is dead, or while the circuit breaker is open —
                # K8s pulls the pod from Service rotation until it
                # recovers (docs/RESILIENCE.md).
                ok, reason = server.health()
                if not ok:
                    self._send(503, {"ok": False, "reason": reason},
                               headers={"Retry-After": "1"})
                    return
                import jax

                self._send(200, {"ok": True, "role": server.role,
                                 "devices": [str(d) for d in jax.devices()]})
            elif self.path == "/livez":
                # LIVENESS: process-up only. Deliberately breaker-blind —
                # restarting a pod because its backend trips the breaker
                # would turn a containable fault into a crash loop.
                self._send(200, {"ok": True})
            elif self.path == "/v1/models":
                self._send(200, server.model_card())
            elif self.path == "/metrics":
                # Content negotiation: exemplars are OpenMetrics-only
                # syntax, so they appear ONLY when the scraper asks for
                # that format. The default exposition stays byte-
                # identical to the pre-exemplar format.
                accept = self.headers.get("Accept", "")
                if "application/openmetrics-text" in accept:
                    body = server.openmetrics().encode()
                    ctype = ("application/openmetrics-text; "
                             "version=1.0.0; charset=utf-8")
                else:
                    body = server.prometheus_metrics().encode()
                    ctype = "text/plain; version=0.0.4"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-K3STPU-Replica", server.instance)
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/requests"):
                q = parse_qs(urlparse(self.path).query)
                try:
                    n = int(q.get("n", ["50"])[0])
                except ValueError:
                    self._send(400, {"error": "n must be an integer"})
                    return
                self._send(200, server.debug_timelines(n))
            elif self.path.startswith("/debug/trace"):
                self._send(200, server.debug_trace())
            elif self.path == "/debug/drain":
                self._send(200, server.drain_status())
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            # Trace identity first: even a drain-window 503 must echo a
            # traceparent so the client can correlate the retry chain.
            self._begin_trace()
            if self.path.startswith("/v1/"):
                if server.draining:
                    # Drain window: in-flight requests finish, new work is
                    # shed with an explicit retryable status so clients
                    # fail over to a live replica.
                    self._send(503, {"error": "server draining"},
                               headers={"Retry-After": "1"})
                    return
                # In-flight accounting: main()'s SIGTERM drainer waits for
                # this to hit zero before stopping the listener.
                server.http_begin()
                try:
                    self._route_post()
                finally:
                    server.http_end()
                return
            self._route_post()

        def _route_post(self):
            if self.path.startswith("/debug/profile"):
                q = parse_qs(urlparse(self.path).query)
                try:
                    seconds = float(q.get("seconds", ["3"])[0])
                except ValueError:
                    self._send(400,
                               {"error": "seconds must be a number"})
                    return
                try:
                    path = server.debug_profile(seconds)
                except RuntimeError as e:  # capture already in flight
                    self._send(409, {"error": str(e)})
                    return
                self._send(200, {"artifact": path})
                return
            if self.path == "/v1/score":
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length))
                    lp = server.score_tokens(req["tokens"])
                    self._send(200, {
                        "logprobs": lp,
                        "nll": [-float(np.mean(r)) for r in lp],
                    })
                except (KeyError, ValueError, TypeError, OverflowError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                return
            if self.path == "/v1/prefill":
                # Disagg handoff (docs/DISAGG.md): a decode peer (or the
                # router on its behalf) asks this replica to prefill a
                # prompt and ship the finished KV page chain. The body is
                # raw octet-stream — the checksummed HostPageStore wire
                # format, fed verbatim to the peer's import_chain.
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length))
                    data = server.export_kv(req["prompt_tokens"],
                                            adapter=req.get("adapter"))
                except (KeyError, ValueError, TypeError, OverflowError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except TimeoutError as e:
                    self._send(503, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 — backend failure
                    # A chaos/backend fault inside the export dispatch
                    # fails THIS handoff cleanly; the decode peer counts
                    # a transfer fallback and prefills cold.
                    self._send(500, {"error": str(e)})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-K3STPU-Replica", server.instance)
                self.end_headers()
                self.wfile.write(data)
                return
            if self.path == "/v1/generate":
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length))
                    server.maybe_disagg_prefetch(
                        req.get("prompt_tokens"), req.get("adapter"),
                        self.headers.get("X-K3STPU-Prefill-Endpoint"))
                    kwargs = dict(
                        max_new_tokens=req.get("max_new_tokens", 32),
                        temperature=req.get("temperature", 0.0),
                        top_k=req.get("top_k"),
                        top_p=req.get("top_p"),
                        eos_id=req.get("eos_id"),
                        num_samples=req.get("num_samples", 1),
                        adapter=req.get("adapter"),
                        session=req.get("session"),
                        synthetic=bool(self.headers.get(CANARY_HEADER)),
                        priority=(req.get("priority")
                                  or self.headers.get(PRIORITY_HEADER)
                                  or "interactive"),
                        deadline_ms=req.get("deadline_ms"))
                    if req.get("stream"):
                        events = server.generate_stream(
                            req["prompt_tokens"],
                            trace_id=self._trace_id(), **kwargs)
                        self._send_sse(events)
                        return
                    tokens = server.generate_tokens(
                        req["prompt_tokens"],
                        trace_id=self._trace_id(), **kwargs)
                    self._send(200, {"tokens": tokens})
                except (KeyError, ValueError, TypeError, OverflowError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                except TimeoutError as e:
                    # Engine queue backlog exceeded the wait budget: a
                    # clean 503 beats an http.server traceback + reset.
                    self._send(503, {"error": str(e)})
                except AdmissionRejected as e:
                    # Predictive admission control (docs/QOS.md): the
                    # class TTFT SLO would be breached if this request
                    # queued — or a preemption park failed mid-swap.
                    # Retry-After carries the predicted drain time.
                    self._send(503, {"error": str(e)}, headers={
                        "Retry-After": str(max(1, round(e.retry_after_s)))})
                except (EngineOverloaded, EngineStalled) as e:
                    # Admission bound hit (--max-pending) or a watchdog
                    # trip failed the request mid-flight: shed load with
                    # an explicit retryable status.
                    self._send(503, {"error": str(e)},
                               headers={"Retry-After": "1"})
                except CircuitOpen as e:
                    self._send(503, {"error": str(e)}, headers={
                        "Retry-After": str(max(1, round(e.retry_after_s)))})
                except Exception as e:  # noqa: BLE001 — backend failure
                    # Crash-only containment turned a backend failure into
                    # a per-request error; surface it as a JSON 500, not
                    # an http.server traceback + connection reset.
                    self._send(500, {"error": str(e)})
                return
            if self.path == "/v1/session/release":
                # Explicit between-turn demotion: the client says "this
                # session is idle, take its HBM back" instead of waiting
                # for watermark pressure to decide.
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    req = json.loads(self.rfile.read(length))
                    released = server.release_session(
                        req["session"], spill=bool(req.get("spill", False)))
                    self._send(200, {"released": released})
                except (KeyError, ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": str(e)})
                except TimeoutError as e:
                    self._send(503, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — backend failure
                    self._send(500, {"error": str(e)})
                return
            if self.path != "/v1/predict":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length))
                inputs = np.asarray(req["inputs"], dtype=server.input_dtype())
                if inputs.shape[1:] != server.input_shape():
                    raise ValueError(
                        f"expected input shape {server.input_shape()}, "
                        f"got {inputs.shape[1:]}")
                logits = server.predict(inputs)
                top = np.argsort(-logits[..., -1, :] if logits.ndim == 3
                                 else -logits, axis=-1)[:, :5]
                self._send(200, {
                    "top5": top.tolist(),
                    "logits_shape": list(logits.shape),
                })
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._send(400, {"error": str(e)})

    return Handler


def start_telemetry_thread(server: InferenceServer,
                           interval: float = 10.0) -> threading.Thread:
    """Periodic telemetry drop for host tpu-info's MEMORY/UTIL columns.

    Duty cycle = device-busy fraction since the last drop; the file rides
    the /run/k3stpu hostPath to the node (k3stpu/utils/telemetry.py;
    tpu-inference.yaml volumeMounts). Shared by the serving main() and
    loadgen's self-hosted server so any driven run populates the table.
    """
    from k3stpu.utils.telemetry import write_metrics

    def loop() -> None:
        last_busy, last_t = server.busy_seconds(), time.monotonic()
        while True:
            time.sleep(interval)
            busy, now = server.busy_seconds(), time.monotonic()
            # Clamp below at 0: a reset_stats() between drops (warmup,
            # loadgen) makes the busy counter go backwards once.
            duty = int(min(100.0, max(0.0,
                           100.0 * (busy - last_busy)
                           / max(now - last_t, 1e-9))))
            write_metrics(duty_cycle_pct=duty)
            last_busy, last_t = busy, now

    t = threading.Thread(target=loop, daemon=True, name="telemetry")
    t.start()
    return t


def _default_instance(port: int) -> str:
    """hostname:port — in k8s the hostname is the pod name, so this is
    already the unique replica identity; the port disambiguates several
    servers sharing one host (tests, bench's in-process replicas)."""
    import socket

    return f"{socket.gethostname()}:{port}"


def _chaos_from_env():
    """Fault injection for subprocess tests (K3STPU_CHAOS spec string —
    see k3stpu.chaos.chaos_from_env). Unset (the only production state)
    returns None: zero hooks armed, zero overhead."""
    from k3stpu.chaos import chaos_from_env

    return chaos_from_env()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="K3S-TPU inference server")
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "resnet18-tiny", "transformer",
                             "transformer-medium", "transformer-tiny",
                             "moe", "moe-tiny"])
    ap.add_argument("--port", type=int, default=8096)  # jellyfin.yaml:40-42
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--warmup-only", action="store_true",
                    help="build the server, run the warmup compiles, and "
                         "exit 0 without serving. With --compilation-cache "
                         "this incrementally populates the persistent "
                         "cache: each finished program is saved even if a "
                         "later compile dies, so flaky-backend operators "
                         "(and the capture harness) can retry cheap "
                         "bounded pre-warms until the real server boots "
                         "into an all-hits warmup")
    ap.add_argument("--batch-window-ms", type=float, default=5.0,
                    help="coalescing window for concurrent /v1/predict "
                         "requests (0 disables cross-request batching)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="restore trained params from this train_job "
                         "checkpoint directory (volume mount)")
    ap.add_argument("--ckpt-step", type=int, default=None,
                    help="specific step to load (default: latest finalized)")
    ap.add_argument("--shard-devices", type=int, default=None,
                    help="tensor-parallel serving over N local chips "
                         "(default: all local devices when a multi-chip "
                         "pod granted several; 1 = single-chip)")
    ap.add_argument("--tp-shards", type=int, default=1,
                    help="EXPLICIT tensor-parallel width for the serving "
                         "engine: shard attention heads / MLP hidden and "
                         "the paged KV pool across N chips ('model' mesh "
                         "axis) and arm the k3stpu_serve_tp_* metric "
                         "families. Default 1 keeps the monolithic path "
                         "(and its exposition) byte-stable; implies "
                         "--shard-devices N when > 1")
    ap.add_argument("--profile-port", type=int, default=0,
                    help="expose jax.profiler.start_server on this port "
                         "(0 = off); capture with jax.profiler.trace or "
                         "tensorboard's profile plugin")
    ap.add_argument("--quant", default=None,
                    choices=["int8", "int8-dynamic"],
                    help="weight-only int8 serving (transformer LM family):"
                         " projection kernels stored int8 + per-channel "
                         "scales — halves weight HBM traffic for "
                         "bandwidth-bound decode (models/quant.py)")
    ap.add_argument("--kv-cache-dtype", default=None, choices=["int8"],
                    help="store the KV cache int8 (+ per-token-head fp32 "
                         "scales): half the HBM per cached token, so the "
                         "chip holds ~2x the context length x batch; "
                         "composes with --quant")
    ap.add_argument("--continuous-batching", action="store_true",
                    help="slot-based decode scheduling for /v1/generate "
                         "(serve/engine.py): concurrent generations share "
                         "one decode batch and new requests join mid-"
                         "flight instead of queueing behind long ones")
    ap.add_argument("--engine-slots", type=int, default=8,
                    help="decode slots (max concurrent generation rows) "
                         "for --continuous-batching")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="with --continuous-batching: admit long prompts "
                         "in chunks of this many tokens, decode steps "
                         "interleaved — bounds the decode stall an "
                         "arriving prompt causes to one chunk's latency")
    ap.add_argument("--decode-block", type=int, default=4,
                    help="with --continuous-batching: tokens decoded per "
                         "device dispatch (inner lax.scan). Each dispatch "
                         "through a relayed backend costs ~8 ms flat, so "
                         "K>1 amortizes the floor K-fold; new requests "
                         "join on block boundaries (K-token granularity)")
    ap.add_argument("--lora-adapters", default=None,
                    help="comma list name=ckpt_dir: serve N LoRA "
                         "fine-tunes of one base (S-LoRA). Requests pick "
                         "theirs via {\"adapter\": name}; omitted = base. "
                         "Adapters must share one rank and be trained "
                         "from the served base (train_job --lora-rank)")
    ap.add_argument("--max-pending", type=int, default=None,
                    help="with --continuous-batching: reject new generate "
                         "requests with 503 once this many are in flight "
                         "(queued or decoding) — bounded admission beats "
                         "unbounded queueing under overload. Default: "
                         "unbounded")
    ap.add_argument("--prompt-cache", type=int, default=0,
                    help="with --continuous-batching: LRU-cache this many "
                         "prefilled prompt KV rows — a repeat prompt skips "
                         "its prefill, a prompt extending a cached one "
                         "prefills only the suffix (chat/system-prompt "
                         "reuse). Costs one cache row of HBM per entry")
    ap.add_argument("--kv-page-size", type=int, default=None,
                    help="with --continuous-batching: PAGED KV cache — "
                         "slots hold chains of this-many-token pages from "
                         "a shared pool instead of monolithic max-seq "
                         "rows; admission is bounded by free pages, and "
                         "the prompt cache shares pages zero-copy. "
                         "Must divide --seq-len")
    ap.add_argument("--kv-pages", type=int, default=None,
                    help="page-pool size (incl. the reserved sink page "
                         "0); default = dense parity (slots * seq_len / "
                         "page_size + 1) — set LOWER to spend less HBM "
                         "than dense for the same slot count")
    ap.add_argument("--attn-backend", default="xla-gather",
                    choices=["xla-gather", "pallas-paged"],
                    help="with --kv-page-size: how decode reads the KV "
                         "pool. xla-gather materializes gathered pages "
                         "in XLA (default); pallas-paged walks block "
                         "tables inside the fused Pallas kernel "
                         "(ops/paged_attention.py) — token-identical "
                         "greedy output, no gather materialization. "
                         "Off TPU the kernel runs interpreted (tests "
                         "only)")
    ap.add_argument("--draft-model", default=None,
                    choices=["transformer", "transformer-tiny"],
                    help="speculative decoding draft for greedy "
                         "/v1/generate: the draft proposes --spec-gamma "
                         "tokens per round, the target verifies them in "
                         "one chunked forward; output is exactly the "
                         "target's greedy continuation")
    ap.add_argument("--draft-ckpt-dir", default=None,
                    help="checkpoint dir for the draft model's weights")
    ap.add_argument("--speculate", action="store_true",
                    help="model-free speculative decoding inside the "
                         "continuous-batching engine: an n-gram prompt-"
                         "lookup drafter proposes up to --spec-gamma "
                         "tokens per slot, one batch-wide extend "
                         "verifies them; greedy output is token-"
                         "identical to the plain engine. Requires "
                         "--continuous-batching and --kv-page-size")
    ap.add_argument("--spec-gamma", type=int, default=4)
    ap.add_argument("--tier-host-mb", type=int, default=None,
                    help="with --kv-page-size and --prompt-cache: host-"
                         "RAM budget (MiB) for the KV page tier "
                         "(serve/tiering.py) — released/evicted session "
                         "chains park in host memory and restore bit-"
                         "exactly on the session's next turn, turning "
                         "idle-session capacity from an HBM number "
                         "into a host-RAM number")
    ap.add_argument("--tier-dir", default=None,
                    help="with --tier-host-mb: spill directory for the "
                         "disk tier — chains evicted past the host-RAM "
                         "budget go to checksummed files here instead "
                         "of being dropped")
    ap.add_argument("--tier-watermark", type=int, default=0,
                    help="with --tier-host-mb: when free pages drop "
                         "below this, the engine demotes cold prompt-"
                         "cache chains to the host tier until the pool "
                         "recovers (0 = demote only on explicit "
                         "session release / LRU eviction)")
    ap.add_argument("--watchdog-s", type=float, default=120.0,
                    help="with --continuous-batching: fail blocked "
                         "clients with retryable 503s when the engine "
                         "loop makes no progress for this long, and "
                         "revive a dead loop thread. Must exceed the "
                         "worst single dispatch incl. cold compiles. "
                         "0 disables")
    ap.add_argument("--breaker-threshold", type=int, default=5,
                    help="with --continuous-batching: consecutive "
                         "backend failures that open the circuit "
                         "breaker (/healthz goes not-ready until a "
                         "half-open probe succeeds). 0 disables")
    ap.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                    help="seconds the breaker stays open before "
                         "admitting a half-open probe request")
    ap.add_argument("--drain-deadline-s", type=float, default=25.0,
                    help="on SIGTERM: wait at most this long for "
                         "in-flight requests before stopping the "
                         "listener. Keep it BELOW the pod's "
                         "terminationGracePeriodSeconds or the kubelet "
                         "SIGKILLs mid-drain")
    ap.add_argument("--instance", default=None,
                    help="replica identity (pod name or host:port) "
                         "stamped on the k3stpu_build_info instance "
                         "label and the X-K3STPU-Replica response "
                         "header. Default: hostname:port — in k8s the "
                         "hostname IS the pod name")
    ap.add_argument("--role", default="monolithic",
                    choices=["monolithic", "prefill", "decode"],
                    help="disaggregated serving role (docs/DISAGG.md). "
                         "prefill: answers /v1/prefill with serialized "
                         "KV page chains for decode peers. decode: "
                         "pulls each prompt's chain from its prefill "
                         "peer before admission, so decode never pays "
                         "prefill interference. monolithic (default): "
                         "both phases in-process, nothing changes. "
                         "Non-monolithic roles require "
                         "--continuous-batching, --kv-page-size, and "
                         "--prompt-cache > 0")
    ap.add_argument("--prefill-upstream", default=None,
                    help="with --role decode: base URL of the prefill "
                         "peer to pull KV chains from when the request "
                         "carries no X-K3STPU-Prefill-Endpoint header "
                         "(the router injects that header per request)")
    ap.add_argument("--qos", action="store_true",
                    help="SLO-aware QoS (docs/QOS.md): priority classes on "
                         "/v1/generate, class-weighted prefill budgeting, "
                         "predictive admission control, and tier-backed "
                         "loss-free preemption of batch requests; requires "
                         "--continuous-batching")
    ap.add_argument("--qos-classes", default="interactive,batch",
                    metavar="CLASSES",
                    help="comma-separated priority class set (only "
                         "'interactive,batch' is supported; the flag "
                         "exists so the chart's class list renders "
                         "explicitly)")
    ap.add_argument("--interactive-ttft-slo-ms", type=float, default=2500.0,
                    metavar="MS",
                    help="interactive-class TTFT SLO: predictive admission "
                         "rejects an interactive request with 503 + "
                         "Retry-After when its forecast TTFT exceeds this")
    ap.add_argument("--batch-ttft-slo-ms", type=float, default=30000.0,
                    metavar="MS",
                    help="batch-class TTFT SLO for predictive admission "
                         "(batch tolerates long queues; this bounds them)")
    ap.add_argument("--compilation-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache (volume mount): "
                         "a restarted pod reuses compiled programs instead "
                         "of paying every JIT again — the Recreate-strategy "
                         "restart goes from minutes of warmup to seconds")
    args = ap.parse_args(argv)

    if args.compilation_cache:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          args.compilation_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        print(f"compilation cache at {args.compilation_cache}", flush=True)

    if args.profile_port:
        import jax

        jax.profiler.start_server(args.profile_port)
        print(f"profiler server on :{args.profile_port}", flush=True)

    server = InferenceServer(model_name=args.model,
                             image_size=args.image_size, seq_len=args.seq_len,
                             batch_window_ms=args.batch_window_ms,
                             shard_devices=args.shard_devices,
                             tp_shards=args.tp_shards,
                             ckpt_dir=args.ckpt_dir,
                             ckpt_step=args.ckpt_step,
                             quant=args.quant,
                             kv_cache_dtype=args.kv_cache_dtype,
                             continuous_batching=args.continuous_batching,
                             engine_slots=args.engine_slots,
                             prefill_chunk=args.prefill_chunk,
                             decode_block=args.decode_block,
                             prompt_cache=args.prompt_cache,
                             max_pending=args.max_pending,
                             kv_page_size=args.kv_page_size,
                             kv_pages=args.kv_pages,
                             attn_backend=args.attn_backend,
                             lora_adapters=args.lora_adapters,
                             draft_model=args.draft_model,
                             draft_ckpt_dir=args.draft_ckpt_dir,
                             speculate=args.speculate,
                             spec_gamma=args.spec_gamma,
                             tier_host_mb=args.tier_host_mb,
                             tier_dir=args.tier_dir,
                             tier_watermark=args.tier_watermark,
                             watchdog_s=args.watchdog_s or None,
                             breaker_threshold=(args.breaker_threshold
                                                or None),
                             breaker_cooldown_s=args.breaker_cooldown_s,
                             instance=args.instance or _default_instance(
                                 args.port),
                             role=args.role,
                             prefill_upstream=args.prefill_upstream,
                             chaos=_chaos_from_env(),
                             qos=args.qos,
                             qos_classes=args.qos_classes,
                             interactive_ttft_slo_ms=(
                                 args.interactive_ttft_slo_ms),
                             batch_ttft_slo_ms=args.batch_ttft_slo_ms)
    if server.loaded_step is not None:
        print(f"loaded checkpoint step {server.loaded_step} "
              f"from {args.ckpt_dir}", flush=True)
    if not args.no_warmup:
        print("warming up (pre-compiling batch sizes)...", flush=True)
        server.warmup()
    if args.warmup_only:
        if args.no_warmup:
            # A silent rc=0 here would tell retry loops the cache is
            # populated when nothing compiled.
            print("--warmup-only with --no-warmup compiles nothing",
                  flush=True)
            server.close()
            return 2
        print("warmup complete (--warmup-only); exiting", flush=True)
        server.close()
        return 0

    start_telemetry_thread(server)
    httpd = ThreadingHTTPServer(("0.0.0.0", args.port), make_app(server))
    # ThreadingHTTPServer defaults daemon_threads=True, and socketserver
    # does not TRACK daemon handler threads — server_close() would then
    # return while handlers are mid-request and server.close() below
    # would yank the engine out from under them. Non-daemon threads are
    # tracked and joined by server_close() (block_on_close), which is
    # exactly the "in-flight requests finish" the drain promises; the
    # k8s grace period bounds the join, and the second-signal escape
    # hatch above covers a wedged handler.
    httpd.daemon_threads = False

    # Graceful pod termination (the Recreate-strategy restart path,
    # reference jellyfin.yaml:13-14): on SIGTERM/SIGINT stop accepting,
    # let in-flight requests finish, release the dispatcher/engine
    # threads, and exit 0 — a chip-holding singleton killed mid-batch
    # would otherwise strand clients and (on a shared chip) leave its
    # process claim to time out. K8s default grace is 30 s; the drain
    # must complete inside it or the kubelet SIGKILLs anyway.
    import signal

    draining = {"on": False}

    def _drain(signum, frame):
        if draining["on"]:
            # Second signal: the drain is stuck (e.g. a handler thread
            # wedged on a dead device dispatch) — restore default
            # disposition so one more signal hard-kills; don't strand
            # the operator behind an unjoinable thread.
            print(f"signal {signum} again: next one is fatal", flush=True)
            signal.signal(signum, signal.SIG_DFL)
            return
        draining["on"] = True
        # New /v1 work gets 503 + Retry-After and /healthz goes
        # not-ready immediately (endpoint removal starts NOW, not when
        # the listener dies) — only then is the listener stopped, once
        # in-flight requests finish or the drain deadline passes.
        server.begin_drain()
        print(f"signal {signum}: draining (no new connections; "
              "in-flight requests finish)...", flush=True)

        def _drainer():
            deadline = time.monotonic() + args.drain_deadline_s
            while (server.active_http_requests() > 0
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if server.active_http_requests() > 0:
                print(f"drain deadline ({args.drain_deadline_s:.0f}s) "
                      f"passed with requests in flight; stopping anyway",
                      flush=True)
            # shutdown() blocks until serve_forever exits; this thread is
            # already off the signal frame.
            httpd.shutdown()

        threading.Thread(target=_drainer, daemon=True).start()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    print(f"serving {args.model} on :{args.port}", flush=True)
    httpd.serve_forever()          # returns after _drain fires
    httpd.server_close()
    server.close()                 # drain batcher + engine threads
    print("drained; bye", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
