"""Scheduler: admission control, chunked prefill budgeting, and the
continuous-batching policy of the decomposed engine (docs/DISAGG.md).

Owns the request lifecycle — packing/validation, backpressure, the
pending queue, chunked admission, slot activation, deadlines, and
completion — and dispatches device work through the model runner
(serve/runner.py) against KV state owned by the page manager
(serve/kv_manager.py). ``GenerateEngine`` composes the three as mixins
over one shared ``self``; behavior is pinned by the pre-split
bit-exactness suites."""

from __future__ import annotations

import queue
import threading
import time

import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import set_cache_index
from k3stpu.obs.slo import admission_retry_after, predict_ttft
from k3stpu.serve.containment import CircuitOpen
from k3stpu.serve.programs import prompt_width_bucket
from k3stpu.serve.runner import _pow2_at_least

# QoS priority classes (docs/QOS.md). "interactive" is the default for
# unlabeled traffic ON PURPOSE: classless deployments keep exactly the
# pre-QoS behavior (never preempted, never class-shed), and batch is an
# explicit opt-in to delay-tolerance.
QOS_CLASSES = ("interactive", "batch")


def _validated_priority(priority: str) -> str:
    if priority not in QOS_CLASSES:
        raise ValueError(
            f"priority must be one of {QOS_CLASSES}, got {priority!r}")
    return priority


# Interactive's share of the per-tick chunked-prefill token budget on a
# qos=True engine (batch gets the rest; an empty class donates its
# share). 3:1, not 1:0 — batch must keep a guaranteed prefill trickle
# under sustained interactive load or its clients time out holding
# admission tokens, which is worse than slow.
QOS_INTERACTIVE_SHARE = 0.75


class EngineOverloaded(RuntimeError):
    """Raised by submit paths when max_pending requests are already in
    flight — the backpressure signal the HTTP layer turns into a 503
    (shed load at the door; queueing unboundedly just converts overload
    into client timeouts plus held memory)."""


class AdmissionRejected(RuntimeError):
    """Predictive admission control refused this request: the TTFT
    forecast (queue depth + prefill backlog over the measured p50 —
    ``k3stpu.obs.slo.predict_ttft``) breaches the class SLO, so the
    honest answer is an immediate 503 with ``Retry-After`` instead of a
    queued timeout. Also raised when a preemption park fails mid-swap:
    the victim keeps running and THIS request is turned away."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class _Request:
    __slots__ = ("block", "lens", "budget", "temp", "top_k", "top_p",
                 "eos", "event", "tokens", "error", "slot_rows", "samples",
                 "deadline", "stream_q", "_ptuple", "probe", "adapter",
                 "trace", "trace_id", "session", "synthetic", "priority",
                 "preempted_tokens")

    def __init__(self, block, lens, budget, temp, top_k, eos, samples=1,
                 top_p=None, adapter=0):
        self.block = block          # (n, P) int32, right-padded
        self.lens = lens            # (n,) true lengths
        self.budget = budget        # max new tokens (shared by the rows)
        self.temp = temp
        self.top_k = top_k
        self.top_p = top_p          # float | None (None == 1.0, no cut)
        self.eos = eos              # int | None
        self.samples = samples      # >1: one prompt, n sampled rows
        self.adapter = adapter      # multi-LoRA slot (0 = base)
        self.event = threading.Event()
        self.tokens: "list[list[int]] | None" = None
        self.error: "Exception | None" = None
        self.slot_rows: "list[int]" = []
        self.deadline: float = float("inf")  # set by _enqueue_and_wait
        # submit_stream() installs a queue here; the loop thread pushes
        # per-block token deltas into it and signal() pushes the terminal
        # None. Non-streaming requests leave it None (zero overhead).
        self.stream_q: "queue.SimpleQueue | None" = None
        self._ptuple: "tuple | None" = None  # memoized prompt key
        # Lifecycle trace (k3stpu.obs.ReqTrace), set at enqueue when the
        # engine carries a ServeObs; None costs nothing on any path.
        self.trace = None
        # W3C trace id (32 validated lowercase-hex chars) assigned at
        # the HTTP edge; None for direct submits. Only parse_traceparent
        # output ever lands here — raw header bytes never reach the
        # engine.
        self.trace_id: "str | None" = None
        # Memoized prompt-cache probe result (pkey, pentry) — the probe
        # re-runs every loop iteration while the request waits for free
        # slots, and re-scanning the cache each time is pure engine-
        # thread waste. A stale entry stays CORRECT (immutable arrays);
        # the only cost is missing a better prefix inserted meanwhile.
        self.probe: "tuple | None" = None
        # Session id (paged mode): names this request's finished KV
        # chain in the prompt cache / host tier so the session's next
        # turn restores it instead of re-prefilling. None = one-shot.
        self.session: "str | None" = None
        # Canary-probe flag (X-K3STPU-Canary at the HTTP edge): the
        # request runs on the ordinary path but its latencies stay out
        # of the organic histograms (ServeObs hooks read it from trace
        # meta).
        self.synthetic = False
        # QoS priority class (docs/QOS.md). Unlabeled traffic is
        # "interactive": classless deployments keep pre-QoS behavior
        # exactly, and only explicit "batch" requests are preemptible /
        # shed-first.
        self.priority = "interactive"
        # Tokens this request emitted BEFORE being preempted (loss-free
        # preemption, paged+tier engines): the requeued continuation
        # decodes only the remaining budget, and _maybe_complete
        # prepends these so the client sees one uninterrupted stream —
        # token-identical to a never-preempted run.
        self.preempted_tokens: "list[int]" = []

    def ptuple(self) -> tuple:
        """The single-prompt cache key, computed once — the admission
        probe re-runs while a request waits for free slots, and an
        O(prompt) conversion per loop iteration on the engine thread
        is waste (the block is immutable after packing)."""
        if self._ptuple is None:
            self._ptuple = tuple(
                int(t) for t in self.block[0, :int(self.lens[0])])
        return self._ptuple

    def signal(self) -> None:
        """Wake the submitter on EVERY terminal path (tokens ready, error,
        expiry, shutdown): terminal stream marker first, THEN the event —
        a streaming consumer must never wait on a queue nobody will feed
        again. Being the single terminal funnel, this is also where the
        lifecycle trace retires (finish() is idempotent — the success
        path already closed it with completion timings)."""
        if self.trace is not None:
            if self.error is not None:
                self.trace.finish("error", repr(self.error))
            else:
                self.trace.finish("ok")
        if self.stream_q is not None:
            self.stream_q.put(None)
        self.event.set()


class _TierCommand:
    """A control message riding the request queue: allocator / prompt
    cache / tier state belongs to the loop thread alone, so HTTP-thread
    operations on it (session release, disagg KV export/import) marshal
    through ``_q`` and run inline at drain. Duck-types the slice of
    ``_Request`` the loop's shutdown tail touches (``error`` +
    ``signal()`` + ``deadline``) so a command stranded behind the close
    sentinel fails cleanly instead of hanging its caller."""

    __slots__ = ("kind", "session", "spill", "event", "result", "error",
                 "deadline", "tokens", "stream_q", "trace", "payload")

    def __init__(self, kind: str, session: str, spill: bool = False,
                 payload=None):
        self.kind = kind
        self.session = session
        self.spill = spill
        self.payload = payload  # export: (prompt, adapter); import: bytes
        self.event = threading.Event()
        self.result = None
        self.error: "Exception | None" = None
        self.deadline = float("inf")  # commands never expire
        self.tokens = None
        self.stream_q = None
        self.trace = None

    def signal(self) -> None:
        self.event.set()


class SchedulerMixin:
    """Admission, backpressure, chunked prefill, slot activation, and
    completion. Owns no state of its own — ``self`` is the composed
    ``GenerateEngine``."""

    # Injectable wall clock for every policy-visible time read (request
    # deadlines, queue expiry). The engine overrides this from its
    # ``clock=`` kwarg; the class default keeps the mixin usable on any
    # duck-typed host. The simulator (k3stpu/sim) swaps in a virtual
    # clock so deadline/admission policy runs at simulated time.
    _clock = staticmethod(time.time)

    # --- client API -----------------------------------------------------

    def _packed_request(self, prompts, max_new_tokens, temperature, top_k,
                        eos_id, samples=1, top_p=None,
                        adapter_id=0) -> "_Request":
        """Shared validation + packing for both entry points: right-pad to
        a pow2 width bucket and bound against the cache."""
        adapter_id = int(adapter_id)
        if adapter_id != 0 and self.n_adapters is None:
            raise ValueError("this engine's model has no adapter stacks "
                             "(multi_lora is off); adapter_id must be 0")
        if self.n_adapters is not None \
                and not 0 <= adapter_id < self.n_adapters:
            raise ValueError(f"adapter_id {adapter_id} outside "
                             f"[0, {self.n_adapters})")
        lens = [len(p) for p in prompts]
        if min(lens) == 0:
            raise ValueError("prompts must be non-empty")
        width = prompt_width_bucket(max(lens), self.max_seq)
        if max(lens) > width or width + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {max(lens)} + budget {max_new_tokens} exceeds the "
                f"cache ({self.max_seq})")
        if self.paged:
            # A request whose WORST-CASE page need (no cache sharing)
            # exceeds the pool would wait in the queue forever — reject
            # at the door instead of deadlocking admission.
            ps = self.page_size
            if samples > 1:
                total = self._pages_for(lens[0], max_new_tokens)
                worst = total + (samples - 1) * (total - lens[0] // ps)
            else:
                worst = sum(self._pages_for(l, max_new_tokens)
                            for l in lens)
            ins = 1 if (self.prompt_cache > 0 and len(prompts) == 1) else 0
            if worst + ins > self._alloc.total:
                raise ValueError(
                    f"request needs up to {worst + ins} pages but the "
                    f"pool has {self._alloc.total} usable — raise "
                    f"num_pages or shrink prompt/budget")
        block = np.zeros((len(prompts), width), np.int32)
        for i, p in enumerate(prompts):
            block[i, :len(p)] = p
        return _Request(block, np.asarray(lens, np.int32), max_new_tokens,
                        float(temperature), top_k, eos_id, samples=samples,
                        top_p=top_p, adapter=adapter_id)

    def _reject_if_full_locked(self) -> None:
        """Caller holds self._lock. Raises EngineOverloaded (counted in
        the rejected stat) when max_pending is exhausted."""
        if (self.max_pending is not None
                and self._inflight >= self.max_pending):
            self._stats["rejected"] += 1
            raise EngineOverloaded(
                f"engine at capacity: {self._inflight} requests in "
                f"flight (max_pending={self.max_pending})")

    def _breaker_gate(self) -> bool:
        """Circuit-breaker admission gate. Returns True when this caller
        holds the half-open probe lease; raises CircuitOpen (counted in
        breaker_rejected) when the breaker refuses traffic."""
        br = self.breaker
        if br is None:
            return False
        admitted, probe = br.allow()
        if not admitted:
            retry = br.retry_after_s()
            with self._lock:
                self._stats["breaker_rejected"] += 1
            raise CircuitOpen(
                f"circuit breaker open after repeated backend failures; "
                f"retry in {retry:.1f}s", retry_after_s=retry)
        return probe

    def take_admission_token(self) -> None:
        """Claim one unit of max_pending or raise EngineOverloaded.
        Callers that split ONE logical request into several chunk
        submits (the server's wider-than-slots path) take ONE token for
        the whole request and pass ``admitted=True`` to the submits —
        re-gating per chunk would reject an already-admitted request
        mid-flight after burning its earlier chunks' decode work."""
        probe = self._breaker_gate()
        try:
            with self._lock:
                self._reject_if_full_locked()
                self._inflight += 1
        except EngineOverloaded:
            if probe:
                # The half-open probe lost the capacity race before
                # reaching the backend — return the lease so the next
                # arrival can probe instead of waiting out the window.
                self.breaker.probe_aborted()
            raise

    def release_admission_token(self) -> None:
        with self._lock:
            self._inflight -= 1

    def at_capacity(self) -> bool:
        """Advisory (racy by nature): lets the HTTP layer 503 BEFORE
        committing response headers; the authoritative check is the
        token take in the submit paths."""
        with self._lock:
            return (self.max_pending is not None
                    and self._inflight >= self.max_pending)

    def reject_if_at_capacity(self) -> None:
        """Advisory shed WITHOUT claiming a token: raises
        EngineOverloaded (counted in the rejected stat, same as an
        authoritative take failure) when at capacity. For callers that
        must 503 before response headers but defer the real token take
        until their generator actually starts."""
        br = self.breaker
        if br is not None and br.state() == "open":
            retry = br.retry_after_s()
            with self._lock:
                self._stats["breaker_rejected"] += 1
            raise CircuitOpen(
                f"circuit breaker open after repeated backend failures; "
                f"retry in {retry:.1f}s", retry_after_s=retry)
        with self._lock:
            self._reject_if_full_locked()

    # --- predictive admission control (QoS; submitter threads) ----------

    def _admission_forecast(self, priority: str) -> "float | None":
        """TTFT forecast for a request of ``priority`` arriving NOW,
        from this replica's own signals: the obs TTFT p50 (the same
        bucket math the autoscaler's scrape derives — obs.hist.hist_p50
        over the rendered family equals Histogram.quantile(0.5) here)
        plus live queue depth and prefill backlog. Interactive requests
        count only the interactive queue ahead of them — the
        class-ordered admission walk means batch backlog cannot delay
        them (preemption reclaims slots). Reads of the loop-owned
        pending list are snapshot copies (atomic under the GIL) — the
        forecast is advisory, so a stale element is noise, not a bug.

        None = no basis to reject (no latency history, obs off, or the
        chaos point ``admission_predict`` fired — the estimator FAILS
        OPEN: a broken predictor must degrade to the pre-QoS FIFO
        behavior, never to rejecting everything)."""
        try:
            if self._chaos is not None:
                self._chaos.fire("admission_predict")
            if self._obs is None:
                return None
            p50 = self._obs.ttft.quantile(0.5)
            if p50 is None:
                return None
            pend = list(self._pending)
            if priority != "batch":
                pend = [r for r in pend
                        if getattr(r, "priority", "interactive")
                        != "batch"]
            backlog = sum(int(r.lens.sum()) for r in pend)
            depth = len(pend) + self._q.qsize()
            return predict_ttft(
                p50, depth, backlog, self.slots,
                self.chunk_prefill if self.chunk_prefill is not None
                else self.max_seq)
        except Exception:  # noqa: BLE001 — estimator down ≠ service down
            with self._lock:
                self._stats["predict_fallbacks"] += 1
            return None

    def _class_slo_s(self, priority: str) -> "float | None":
        return (self.batch_ttft_slo_s if priority == "batch"
                else self.interactive_ttft_slo_s)

    def _qos_admission_gate(self, req: "_Request") -> None:
        """Reject-before-enqueue (engine qos=True): when the forecast
        TTFT breaches the class SLO, raise AdmissionRejected with a
        finite Retry-After sized to the predicted overshoot — overload
        degrades to early honest rejection instead of queued timeouts.
        Canary probes are exempt: the watchdog must see the fleet's
        real serving behavior, and a watchdog blinded by its own
        admission gate can't tell overload from wrongness."""
        if not self.qos or req.synthetic:
            return
        slo = self._class_slo_s(req.priority)
        if slo is None or slo <= 0.0:
            return
        predicted = self._admission_forecast(req.priority)
        if predicted is None or predicted <= slo:
            return
        retry = admission_retry_after(predicted, slo)
        with self._lock:
            self._stats["admission_rejected"] += 1
        if self._obs is not None:
            self._obs.on_admission_rejected(req.priority)
        raise AdmissionRejected(
            f"predicted TTFT {predicted:.2f}s breaches the "
            f"{req.priority} SLO ({slo:.2f}s); retry in {retry:.0f}s",
            retry_after_s=retry)

    def _trace_enqueue(self, req: "_Request", stream: bool = False) -> None:
        """Open the request's lifecycle trace at ingress (submitter
        thread, just before the queue put — so queue wait is measured
        from the moment the loop COULD have seen the request)."""
        if self._obs is not None:
            meta = dict(
                rows=int(req.samples if req.samples > 1
                         else req.block.shape[0]),
                prompt_len=int(max(req.lens)), budget=int(req.budget),
                stream=stream, adapter=int(req.adapter))
            # Only stamp the key when set — keeps organic trace meta
            # byte-identical to the pre-canary layout.
            if req.synthetic:
                meta["synthetic"] = True
            req.trace = self._obs.start_trace(trace_id=req.trace_id, **meta)

    def _enqueue_and_wait(self, req: "_Request", timeout_s: float,
                          admitted: bool = False) -> "list[list[int]]":
        # The loop thread enforces the same deadline: a request whose
        # client gave up is dropped from the queue / its slots freed,
        # instead of decoding its full budget for nobody.
        if not admitted:
            self.take_admission_token()
        try:
            req.deadline = self._clock() + timeout_s
            self._trace_enqueue(req)
            # Waiter registry: the watchdog fails everyone in this set
            # with a retryable error when the loop stalls or dies, so a
            # client blocks for at most ~watchdog_s, never timeout_s.
            with self._lock:
                self._waiters.add(req)
            try:
                self._q.put(req)
                if not req.event.wait(timeout_s + 1.0):
                    raise TimeoutError("generation did not finish in time")
                if req.error is not None:
                    raise req.error
                return req.tokens
            finally:
                with self._lock:
                    self._waiters.discard(req)
        finally:
            if not admitted:
                self.release_admission_token()

    def submit(self, prompts: "list[list[int]]", *, max_new_tokens: int,
               temperature: float = 0.0, top_k: "int | None" = None,
               top_p: "float | None" = None,
               eos_id: "int | None" = None, adapter_id: int = 0,
               timeout_s: float = 600.0, admitted: bool = False,
               trace_id: "str | None" = None,
               session: "str | None" = None,
               synthetic: bool = False,
               priority: str = "interactive") -> "list[list[int]]":
        """Blocking: returns (n, max_new_tokens) token lists.
        ``admitted``: the caller already holds an admission token
        covering this submit (see take_admission_token).
        ``trace_id``: validated W3C trace id for the lifecycle trace.
        ``session``: single-prompt only — names the request's finished
        KV chain so the session's next turn (a prompt extending this
        one's prompt + reply) restores it instead of re-prefilling,
        and so ``release_session`` can park it on the host tier.
        ``priority``: QoS class ("interactive" / "batch"). On a
        qos=True engine, batch requests are preemptible and share a
        minority of the admission budget; either class may be rejected
        at the door (AdmissionRejected) when its TTFT SLO would be
        breached. On a classless engine the label is carried but
        changes nothing."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        if session is not None and n != 1:
            raise ValueError("session requires exactly one prompt "
                             "(a session names ONE chain)")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        req.session = session
        req.synthetic = synthetic
        req.priority = _validated_priority(priority)
        self._qos_admission_gate(req)
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_samples(self, prompt: "list[int]", n: int, *,
                       max_new_tokens: int, temperature: float = 1.0,
                       top_k: "int | None" = None,
                       top_p: "float | None" = None,
                       eos_id: "int | None" = None, adapter_id: int = 0,
                       timeout_s: float = 600.0, admitted: bool = False,
                       trace_id: "str | None" = None,
                       synthetic: bool = False,
                       priority: str = "interactive") -> "list[list[int]]":
        """n sampled continuations of ONE prompt for the price of one
        prefill: the prefilled cache row broadcasts across n slots and the
        rows diverge through per-row sampling noise. (With temperature 0
        all rows are the same greedy continuation — use submit().)"""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not 1 <= n <= self.slots:
            raise ValueError(f"need 1..{self.slots} samples, got {n}")
        req = self._packed_request([prompt], max_new_tokens, temperature,
                                   top_k, eos_id, samples=n, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        req.synthetic = synthetic
        req.priority = _validated_priority(priority)
        self._qos_admission_gate(req)
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_stream(self, prompts: "list[list[int]]", *,
                      max_new_tokens: int, temperature: float = 0.0,
                      top_k: "int | None" = None,
                      top_p: "float | None" = None,
                      eos_id: "int | None" = None, adapter_id: int = 0,
                      timeout_s: float = 600.0, admitted: bool = False,
                      trace_id: "str | None" = None,
                      session: "str | None" = None,
                      synthetic: bool = False,
                      priority: str = "interactive"):
        """Streaming submit(): returns an iterator of events.

        Incremental events are ``{"done": False, "rows": {row: [tok, ...]}}``
        — one per decode dispatch that produced tokens for this request
        (granularity = ``decode_block``; the first event carries each
        row's first token straight off the prefill logits, so
        time-to-first-token is prefill latency). The final event is
        ``{"done": True, "tokens": [[...]]}`` with exactly submit()'s
        return value (greedy exactness stays pinned to ``generate()``).
        Rows that hit eos stop producing deltas; the final tokens are
        eos-extended to the budget like submit()'s. Errors (deadline
        expiry, decode failure, shutdown) raise from the iterator."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        if session is not None and n != 1:
            raise ValueError("session requires exactly one prompt "
                             "(a session names ONE chain)")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        req.session = session
        req.synthetic = synthetic
        req.priority = _validated_priority(priority)
        self._qos_admission_gate(req)
        req.stream_q = queue.SimpleQueue()
        return self._stream_events(req, timeout_s, admitted)

    def _stream_events(self, req: "_Request", timeout_s: float,
                       admitted: bool = False):
        # Same deadline contract as _enqueue_and_wait: the loop thread
        # drops expired requests; this consumer gets the terminal marker
        # and raises the TimeoutError the loop recorded. The admission
        # token spans the generator's life — taken at first next() (no
        # iteration, no enqueue, no token), released in the finally.
        if not admitted:
            self.take_admission_token()
        try:
            yield from self._stream_events_inner(req, timeout_s)
        finally:
            if not admitted:
                self.release_admission_token()

    def _stream_events_inner(self, req: "_Request", timeout_s: float):
        req.deadline = self._clock() + timeout_s
        self._trace_enqueue(req, stream=True)
        with self._lock:
            self._waiters.add(req)
        self._q.put(req)
        hard = req.deadline + 1.0
        try:
            while True:
                try:
                    item = req.stream_q.get(
                        timeout=max(0.0, hard - self._clock()))
                except queue.Empty:
                    raise TimeoutError("generation did not finish in time")
                if item is None:  # terminal: tokens ready or error
                    if req.error is not None:
                        raise req.error
                    yield {"done": True, "tokens": req.tokens}
                    return
                yield {"done": False, "rows": item}
        finally:
            with self._lock:
                self._waiters.discard(req)
            # Consumer abandoned the stream (generator .close() on client
            # disconnect, or an exception in the consumer): expire the
            # request NOW so the loop reaps its queue entry / admission /
            # slots next iteration, instead of decoding the rest of the
            # budget for nobody.
            if req.tokens is None and req.error is None:
                req.deadline = 0.0

    # --- admission (loop thread; owns all slot state) -------------------

    def _free_slots(self) -> "list[int]":
        # A row that finished EARLY (eos) while its multi-row request is
        # still decoding stays owned: its collected tokens feed
        # _maybe_complete, so handing the slot to a new request would
        # clobber them (the stranger's tokens would surface in the
        # finished request's result, and the completion bookkeeping of
        # whichever finishes second corrupts the other's). Owner clears
        # at completion/failure — only then is the slot reusable.
        return [i for i in range(self.slots)
                if not self._active[i] and not self._reserved[i]
                and self._owner[i] is None]

    def _drain_queue(self, block: bool) -> bool:
        """Move queued requests into pending. Returns False on shutdown.
        Tier commands (session release, KV export/import) execute INLINE
        here — they are loop-thread state operations, not admissions, so
        they never enter the pending list or compete with requests for
        slots."""
        try:
            timeout = 0.2 if block else 0.0
            while True:
                req = self._q.get(block=block, timeout=timeout)
                if req is None:
                    return False
                if isinstance(req, _TierCommand):
                    self._exec_tier_command(req)
                else:
                    self._pending.append(req)
                block = False  # only the first get may wait
        except queue.Empty:
            return True

    def _admit(self) -> None:
        """Admit pending requests. Chunked admissions advance ONE chunk
        per call, so an arriving long prompt delays in-flight decode by at
        most one chunk's latency, never the whole prefill. While a
        chunked admission is in flight, ONE short (single-shot) request
        may still slip in per call — no head-of-line blocking behind a
        long prefill when free slots exist."""
        if self._adm is not None:
            self._admission_step()
            self._admit_pending(allow_chunked=False, limit=1)
            return
        self._admit_pending(allow_chunked=True)

    def _admission_walk(self) -> "tuple[list, dict | None]":
        """Admission order + per-tick class prefill budgets. Classless
        engines walk the pending list in arrival order with no budget —
        byte-identical to the pre-QoS scheduler. qos=True walks
        interactive first (FIFO within each class) and splits the
        chunked-prefill token budget QOS_INTERACTIVE_SHARE/rest between
        the classes, work-conserving: a class with nothing pending
        donates its share to the other."""
        if not self.qos:
            return list(self._pending), None
        inter = [r for r in self._pending if r.priority != "batch"]
        batch = [r for r in self._pending if r.priority == "batch"]
        budget = None
        if self.chunk_prefill is not None:
            b = float(self.chunk_prefill)
            budget = {"interactive": QOS_INTERACTIVE_SHARE * b,
                      "batch": (1.0 - QOS_INTERACTIVE_SHARE) * b}
            if not batch:
                budget["interactive"] = b
            if not inter:
                budget["batch"] = b
        return inter + batch, budget

    def _admit_pending(self, *, allow_chunked: bool,
                       limit: "int | None" = None) -> None:
        admitted = 0
        walk, budget_left = self._admission_walk()
        for req in walk:
            if limit is not None and admitted >= limit:
                return
            if (budget_left is not None
                    and budget_left[req.priority] <= 0.0):
                continue  # class prefill budget spent this tick
            # The pow2 bucket is the admission unit: bucket rows beyond n
            # also land in free slots (they must not overwrite live rows),
            # so the fit check runs on nb BEFORE any device work.
            n, width = req.block.shape
            n_rows = req.samples if req.samples > 1 else n
            nb = min(_pow2_at_least(n_rows), self.slots)
            c = self.chunk_prefill
            # Prompt-cache probe (single-prompt requests): an exact hit
            # skips the prefill outright; a prefix hit appends only the
            # suffix — IF that suffix honors the same stall bound a
            # chunked prefill enforces and fits the cache depth.
            prompt = pkey = pentry = None
            if self.prompt_cache > 0 and n == 1:
                prompt = req.ptuple()
                if req.probe is None:
                    pkey, pentry = self._pcache_lookup(prompt, req.adapter)
                    if self._tier is not None:
                        # Tier probe BEFORE declaring a pcache miss: a
                        # host-resident chain longer than the best
                        # device-resident prefix swaps in and the
                        # lookup re-runs — the restored entry then
                        # serves this admission exactly like one that
                        # never left HBM. A failed swap-in already
                        # counted its fallback; the request just
                        # proceeds with whatever the pcache had.
                        tkey = self._tier.match(req.adapter, prompt)
                        with self._lock:
                            self._stats["tier_hits" if tkey is not None
                                        else "tier_misses"] += 1
                        if self._obs is not None:
                            self._obs.on_tier_probe(tkey is not None)
                        if (tkey is not None
                                and (pkey is None
                                     or len(tkey[1]) > len(pkey))
                                and self._tier_swap_in(tkey)):
                            if req.trace is not None:
                                req.trace.event(
                                    "tier_swap_in",
                                    {"cached_len": len(tkey[1])})
                            pkey, pentry = self._pcache_lookup(
                                prompt, req.adapter)
                    if pkey is not None and len(pkey) < len(prompt):
                        g = _pow2_at_least(len(prompt) - len(pkey))
                        if (len(pkey) + g > self.max_seq
                                or (c is not None and g > c)):
                            pkey = pentry = None  # suffix too big
                    req.probe = (pkey, pentry)
                pkey, pentry = req.probe
            chunked = c is not None and width > c and pkey is None
            if chunked and not allow_chunked:
                continue  # long prompts wait for the in-flight one
            free = self._free_slots()
            if len(free) < nb and not chunked:
                outcome = self._preempt_for(req)
                while outcome == "freed" and len(self._free_slots()) < nb:
                    outcome = self._preempt_for(req)
                if outcome == "failed":
                    continue  # park failed: req rejected, walk on
                free = self._free_slots()
            if len(free) < nb:
                return  # strict FIFO on capacity: big requests don't starve
            if self.paged:
                need = self._pages_needed(req, pkey)
                # Pinned prompt-cache pages are reclaimable HBM: evict
                # idle entries (LRU) until the request fits — but never
                # the entry THIS request is about to share (evicting it
                # would cost more fresh pages than it frees).
                while need > self._alloc.free and self._pcache:
                    lru = next(iter(self._pcache))
                    if pkey is not None and lru == (req.adapter, pkey):
                        if len(self._pcache) == 1:
                            break
                        self._pcache[lru] = self._pcache.pop(lru)  # MRU
                        continue
                    freed = self._pcache_evict_lru()
                    with self._lock:
                        self._stats["pcache_bytes"] -= freed
                if need > self._alloc.free and not chunked:
                    outcome = self._preempt_for(req)
                    while outcome == "freed" and need > self._alloc.free:
                        outcome = self._preempt_for(req)
                    if outcome == "failed":
                        continue  # park failed: req rejected, walk on
                if need > self._alloc.free:
                    return  # strict FIFO: decodes must free pages first
            self._pending.remove(req)
            admitted += 1
            if budget_left is not None:
                budget_left[req.priority] -= float(width)
            tr = req.trace
            if self._obs is not None:
                wait = (time.perf_counter() - tr.t_enqueue
                        if tr is not None and tr.t_enqueue is not None
                        else 0.0)
                self._obs.on_admit(tr, wait, slots=nb)
            if pkey is not None:
                exact = len(pkey) == len(prompt)
                with self._lock:
                    self._stats["pcache_hits" if exact
                                else "pcache_prefix_hits"] += 1
                if tr is not None:
                    tr.event("pcache_hit" if exact else "pcache_prefix_hit",
                             {"cached_len": len(pkey)})
                try:
                    if self.paged:
                        self._admit_hit_paged(req, free[:nb], n_rows,
                                              prompt, pkey, pentry)
                        continue
                    if exact:
                        small, last = pentry[0], pentry[1]
                    else:
                        small, last = self._pcache_extend(
                            pentry[0], prompt, len(pkey), req.adapter)
                        self._pcache_insert(prompt, small, last,
                                            req.adapter)
                    if req.samples > 1:
                        small, last = self._broadcast_rows(small, last, nb)
                    self._activate(req, free[:nb], n_rows, small, last)
                except Exception as e:  # noqa: BLE001 — fail the one request
                    self._record_backend_failure()
                    req.error = e
                    req.signal()
                continue
            if prompt is not None:
                with self._lock:
                    self._stats["pcache_misses"] += 1
                if tr is not None:
                    tr.event("pcache_miss")
            if req.samples > 1:
                # Shared-prefix fan-out: prefill the ONE prompt row; the
                # broadcast to nb rows happens at activation/finalize.
                block, lens = req.block, req.lens
            else:
                block = np.zeros((nb, width), np.int32)
                block[:n] = req.block
                lens = np.concatenate(
                    [req.lens, np.ones((nb - n,), np.int32)])
            all_rows = free[:nb]
            if chunked:
                # Start a chunked admission: reserve the slots (and, in
                # paged mode, the page chains — a later admission must
                # not steal pages this one's finalize counts on), run
                # the first chunk, and let subsequent loop iterations
                # (with decode steps in between) carry the rest.
                chains = None
                try:
                    if self.paged:
                        chains = self._alloc_request_chains(
                            req, nb, n_rows, lens)
                    small, _ = self._prefill(
                        self.params, jnp.asarray(block[:, :c]),
                        jnp.full((block.shape[0],), c, jnp.int32),
                        self._aid_arg(block.shape[0], req.adapter))
                except Exception as e:  # noqa: BLE001
                    self._record_backend_failure()
                    self._free_chains(chains)
                    req.error = e
                    req.signal()
                    continue
                for r in all_rows:
                    self._reserved[r] = True
                self._adm = {"req": req, "cache": small, "block": block,
                             "lens": lens, "pos": c, "rows": all_rows,
                             "n": n_rows, "chains": chains}
                with self._lock:
                    self._stats["adm_chunks"] += 1
                if tr is not None:
                    tr.event("prefill_chunk", {"pos": c, "of": width})
                return
            chains = None
            handed = False
            try:
                if self.paged:
                    chains = self._alloc_request_chains(req, nb, n_rows,
                                                        lens)
                small, last = self._prefill(
                    self.params, jnp.asarray(block), jnp.asarray(lens),
                    self._aid_arg(block.shape[0], req.adapter))
                if prompt is not None and not self.paged:
                    # 1-row, pre-broadcast state; the paged engine
                    # inserts AFTER packing (zero-copy page pins).
                    self._pcache_insert(prompt, small, last, req.adapter)
                if req.samples > 1 and not self.paged:
                    small, last = self._broadcast_rows(small, last, nb)
                handed = True
                self._activate(req, all_rows, n_rows, small, last,
                               chains=chains,
                               pinsert=prompt if self.paged else None)
            except Exception as e:  # noqa: BLE001 — fail the one request
                self._record_backend_failure()
                if not handed:
                    self._free_chains(chains)
                req.error = e
                req.signal()
                continue

    # --- loss-free preemption (loop thread; docs/QOS.md) ----------------

    def _preempt_for(self, req: "_Request") -> str:
        """Try to free capacity for interactive ``req`` by parking ONE
        running batch-class row's generation state on the host tier and
        requeueing it as its own continuation. Returns "freed" (caller
        re-checks capacity and may preempt again), "none" (no eligible
        victim — req waits FIFO exactly like the classless engine), or
        "failed" (the park failed mid-swap: the victim keeps running
        untouched and ``req`` was rejected with a Retry-After).

        Eligible victims are single-prompt, single-sample, greedy,
        non-streaming batch requests: greedy because the resumed
        continuation must be token-identical (a sampled row's RNG
        stream is positional state the park does not carry), and
        non-streaming because the client already consumed the parked
        tokens — replaying them through a live stream would emit them
        twice. Among eligible rows the one with the FEWEST collected
        tokens parks (smallest host copy), ties to the highest row."""
        if (not self.qos or not self.paged or self._tier is None
                or req.priority == "batch"):
            return "none"
        victim = None
        for r in range(self.slots):
            o = self._owner[r]
            if o is None or not self._active[r]:
                continue
            if (o.priority != "batch" or o.synthetic or o.samples != 1
                    or o.block.shape[0] != 1 or o.stream_q is not None
                    or o.temp != 0.0):
                continue
            if (victim is None or len(self._collected[r])
                    <= len(self._collected[victim])):
                victim = r
        if victim is None:
            return "none"
        vreq = self._owner[victim]
        t0 = time.perf_counter()
        if not self._preempt_park(vreq, victim):
            # Nothing was mutated: the victim keeps decoding, and the
            # interactive trigger is turned away honestly instead of
            # waiting behind a batch request it was promised priority
            # over.
            with self._lock:
                self._stats["preempt_fallbacks"] += 1
                self._stats["admission_rejected"] += 1
            if self._obs is not None:
                self._obs.on_admission_rejected(req.priority)
            self._pending.remove(req)
            req.error = AdmissionRejected(
                "preemption park failed mid-swap; the running request "
                "keeps its slot — retry shortly", retry_after_s=1.0)
            req.signal()
            return "failed"
        self._preempt_requeue(vreq, victim)
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["preemptions"] += 1
        if self._obs is not None:
            self._obs.on_preempt(dt)
        if vreq.trace is not None:
            vreq.trace.event("preempted",
                             {"row": victim,
                              "emitted": len(vreq.preempted_tokens)})
        return "freed"

    def _preempt_park(self, vreq: "_Request", r: int) -> bool:
        """Copy row ``r``'s generation state to the host tier WITHOUT
        mutating engine state — all-or-nothing, so a failure leaves the
        victim running exactly as before (chaos point ``preempt_park``
        drills this). The parked key is the victim's prompt + every
        emitted token but the LAST: the chain holds K/V for exactly
        those positions (the newest sampled token was never fed back —
        the same invariant ``_session_insert`` relies on), so the
        resume prompt (prompt + ALL emitted tokens) prefix-hits the
        entry and re-decodes one token for exact continuation logits.
        ``last=None`` like a session tail: the entry is a resume point,
        not an exact-hit cache (no stored logits to serve)."""
        try:
            if self._chaos is not None:
                self._chaos.fire("preempt_park")
            toks = self._collected[r]
            key_prompt = vreq.ptuple() + tuple(int(t) for t in toks[:-1])
            n_entry = -(-len(key_prompt) // self.page_size)
            host = self._gather_pages(self._chains[r][:n_entry])
            self._tier.put((vreq.adapter, key_prompt), len(key_prompt),
                           host, last=None)
            return True
        except Exception:  # noqa: BLE001 — containment: park must not kill
            return False   # the loop; the caller degrades per contract

    def _preempt_requeue(self, vreq: "_Request", r: int) -> None:
        """Release the victim's row and mutate the request object into
        its own continuation at the FRONT of the pending queue: prompt
        grows by the emitted tokens, budget shrinks by the same count
        (B - g >= 1 because an active row always has >= 1 budget left).
        The event/trace/deadline/waiter registration all carry over —
        the blocked submitter never notices. Runs ONLY after a
        successful park; on re-admission the tier probe prefix-hits the
        parked chain (or, if it was evicted, a cold prefill of the
        grown prompt — token-identical either way, just slower)."""
        toks = [int(t) for t in self._collected[r]]
        prompt = list(vreq.ptuple()) + toks
        # Row teardown = the _finish_row discipline minus the session
        # insert (the request is NOT finished; its session, if any,
        # inserts when the continuation completes the conversation).
        self._active[r] = False
        self._temps[r] = 0.0
        if self.speculate:
            self._spec_hist[r] = []
        self._owner[r] = None
        self._collected[r] = []
        self._release_slot_pages(r)
        width = prompt_width_bucket(len(prompt), self.max_seq)
        block = np.zeros((1, width), np.int32)
        block[0, :len(prompt)] = prompt
        vreq.block = block
        vreq.lens = np.asarray([len(prompt)], np.int32)
        vreq.budget = vreq.budget - len(toks)
        vreq.preempted_tokens.extend(toks)
        vreq._ptuple = None  # prompt changed; recompute on next use
        vreq.probe = None
        vreq.slot_rows = []
        self._pending.insert(0, vreq)

    def _admission_step(self) -> None:
        """One chunk of the in-flight admission (or its finalize)."""
        a = self._adm
        req, c = a["req"], self.chunk_prefill
        width = a["block"].shape[1]
        try:
            if a["pos"] < width:
                end = min(a["pos"] + c, width)
                a["cache"] = self._extend_chunk(
                    self.params, a["cache"],
                    jnp.asarray(a["block"][:, a["pos"]:end]),
                    self._aid_arg(a["block"].shape[0], req.adapter))
                a["pos"] = end
                with self._lock:
                    self._stats["adm_chunks"] += 1
                if req.trace is not None:
                    req.trace.event("prefill_chunk",
                                    {"pos": end, "of": width})
                return
            # Finalize: every row consumed the padded width (short rows
            # carry junk K/V beyond their length). Reset each row's index
            # to len-1 (free rollback: junk becomes invisible) and decode
            # the row's LAST REAL token — recomputing its K/V in place and
            # yielding the exact first-token logits; index lands on len,
            # the engine's steady-state invariant.
            lens = a["lens"]
            cache = set_cache_index(a["cache"],
                                    jnp.asarray(lens - 1, jnp.int32))
            last_toks = a["block"][np.arange(len(lens)), lens - 1]
            cache, last = self._decode_logits(
                self.params, cache, jnp.asarray(last_toks),
                self._aid_arg(len(lens), req.adapter))
            pinsert = None
            if self.prompt_cache > 0 and a["block"].shape[0] == 1:
                # a["block"] row 0 == req.block row 0 by construction
                # (both admission paths copy it verbatim), so the
                # memoized key is THE key.
                if self.paged:
                    pinsert = a["req"].ptuple()
                else:
                    self._pcache_insert(a["req"].ptuple(), cache, last,
                                        req.adapter)
            if req.samples > 1 and not self.paged:
                cache, last = self._broadcast_rows(cache, last,
                                                   len(a["rows"]))
            for r in a["rows"]:
                self._reserved[r] = False
            # Chain ownership hands to _activate here: an abort after
            # this point must not double-free what the rows now hold.
            chains, a["chains"] = a.get("chains"), None
            self._adm = None
            self._activate(req, a["rows"], a["n"], cache, last,
                           chains=chains, pinsert=pinsert)
        except Exception as e:  # noqa: BLE001 — fail the one request
            self._record_backend_failure()
            self._abort_admission(a, e)

    def _abort_admission(self, a: dict, err: Exception) -> None:
        """The one admission-abort path: release the reserved rows, null
        the in-flight record, and fail its request — in that order, so no
        exit leaves rows reserved for a request nobody is waiting on.
        Takes the record explicitly (NOT via self._adm): the finalize
        branch nulls self._adm before _activate, so an _activate failure
        must still reach the record it was admitting."""
        self._adm = None
        if self.paged:
            self._free_chains(a.get("chains"))
            a["chains"] = None
        for r in a["rows"]:
            self._reserved[r] = False
        a["req"].error = err
        a["req"].signal()

    def _activate(self, req, all_rows, n, small_cache, last_logits,
                  chains=None, pinsert=None) -> None:
        """Install an admitted small cache into the slot block and light
        up the rows (shared tail of both admission paths). Dense engines
        scatter into the monolithic cache; paged engines pack the rows
        into their preallocated page ``chains`` and, when ``pinsert``
        names a prompt, pin the packed pages into the prompt cache
        (zero-copy: full pages shared by incref, tail page copied)."""
        if self.paged:
            last_logits = self._install_paged(req, all_rows, n,
                                              small_cache, last_logits,
                                              chains, pinsert)
        else:
            self._cache = self._scatter(
                self._cache, small_cache, jnp.asarray(all_rows, np.int32))
        self._light_up(req, all_rows, n, last_logits)

    def _install_paged(self, req, all_rows, n, small_cache, last_logits,
                       chains, pinsert):
        """Pack a dense-prefilled admission cache into the rows' page
        chains. samples>1 packs the ONE prompt row and fans it out
        zero-copy: siblings share row 0's full prompt pages (incref) +
        a COW'd tail + their own fresh budget pages — no n-way prompt
        replication in HBM. Returns the (possibly fanned-out)
        first-token logits."""
        ps = self.page_size
        nb = len(all_rows)
        if req.samples > 1:
            L = int(req.lens[0])
            chain0 = chains[0]
            pm = np.zeros((1, self.n_bt), np.int32)
            pm[0, :len(chain0)] = chain0
            self._cache = self._pack_pages(self._cache, small_cache,
                                           jnp.asarray(pm))
            full = L // ps
            row_chains = [chain0]
            for j in range(1, n):
                fresh = chains[j]
                self._alloc.incref(chain0[:full])
                if L % ps:
                    self._cache = self._copy_page(self._cache,
                                                  chain0[full], fresh[0])
                row_chains.append(chain0[:full] + fresh)
            row_lens = [L] * n
        else:
            pm = np.zeros((nb, self.n_bt), np.int32)
            for j in range(n):
                pm[j, :len(chains[j])] = chains[j]
            self._cache = self._pack_pages(self._cache, small_cache,
                                           jnp.asarray(pm))
            row_chains = chains[:n]
            row_lens = [int(x) for x in req.lens]
        if pinsert is not None:
            # Pin row 0's prompt pages before its first decode write
            # lands in the tail page (device ordering follows the
            # self._cache data flow — the COW copy reads the packed,
            # pre-decode state).
            self._pcache_insert_paged(pinsert, row_chains[0],
                                      last_logits[:1], req.adapter)
        for j, r in enumerate(all_rows):
            if j < n:
                self._set_row(r, row_chains[j], row_lens[j])
            else:  # pad rows: sink-page table, dense pad index of 1
                self._set_row(r, [], 1)
        if req.samples > 1:
            last_logits = jnp.broadcast_to(
                last_logits[:1], (nb, *last_logits.shape[1:]))
        return last_logits

    def _admit_hit_paged(self, req, all_rows, n, prompt, pkey,
                         pentry) -> None:
        """Prompt-cache admission without copying the cached prompt K/V:
        every admitted row maps the entry's full pages read-only into
        its block table (incref), copies the partial tail page (the row
        WILL write into it: position L lives there), and takes fresh
        pages for the rest. An exact hit does zero device attention
        work. A prefix hit first materializes row 0 and appends the
        uncached suffix batch-wide with every OTHER row's table pointed
        at the sink page — live rows' pages can't be touched, and their
        device indices are re-injected from the host mirror at the next
        dispatch — then re-decodes the last real token for the exact
        post-prefill logits and shares row 0 into the siblings."""
        ps = self.page_size
        chain0, l0, last0 = pentry[0], pentry[1], pentry[2]
        L, B = len(prompt), req.budget
        total = self._pages_for(L, B)

        def build_row(src_chain, src_len):
            sf = src_len // ps
            fresh = self._alloc.alloc(total - sf)
            if fresh is None:  # fit-checked; defensive
                raise RuntimeError("page pool exhausted mid-admission")
            self._alloc.incref(src_chain[:sf])
            if src_len % ps:
                self._cache = self._copy_page(self._cache,
                                              src_chain[sf], fresh[0])
            return list(src_chain[:sf]) + fresh

        if l0 == L:  # exact hit: host bookkeeping + stored logits only
            row_chains = [build_row(chain0, L) for _ in range(n)]
            last = last0
        else:
            r0 = all_rows[0]
            c0 = build_row(chain0, l0)
            self._set_row(r0, c0, l0)
            bts = np.zeros((self.slots, self.n_bt), np.int32)
            bts[r0] = self._tables[r0]
            idx = self._indices.copy()
            extra = np.asarray(prompt[l0:], np.int32)
            g = _pow2_at_least(len(extra))
            chunk = np.zeros((self.slots, g), np.int32)
            chunk[r0, :len(extra)] = extra
            aids = self._hit_aids(r0, req.adapter)
            self._cache = self._paged_extend(
                self.params, self._cache, jnp.asarray(idx),
                jnp.asarray(bts), jnp.asarray(chunk), aids)
            # Roll back over the suffix pad junk and re-decode the last
            # real token in place (the dense _pcache_extend invariant).
            idx[r0] = L - 1
            toks = np.zeros((self.slots,), np.int32)
            toks[r0] = prompt[-1]
            self._cache, logits = self._paged_decode_logits(
                self.params, self._cache, jnp.asarray(idx),
                jnp.asarray(bts), jnp.asarray(toks), aids)
            last = logits[r0:r0 + 1]
            self._pcache_insert_paged(prompt, c0, last, req.adapter)
            row_chains = [c0] + [build_row(c0, L) for _ in range(1, n)]
        nb = len(all_rows)
        for j, r in enumerate(all_rows):
            if j < n:
                self._set_row(r, row_chains[j], L)
            else:
                self._set_row(r, [], 1)
        if nb > 1:
            last = jnp.broadcast_to(last[:1], (nb, *last.shape[1:]))
        self._light_up(req, all_rows, n, last)

    def _light_up(self, req, all_rows, n, last_logits) -> None:
        """Shared activation tail: first-token sample + slot state."""
        rows = all_rows[:n]
        nb = len(all_rows)
        temps = np.full((nb,), req.temp, np.float32)
        topks = np.full(
            (nb,), req.top_k if req.top_k else self.vocab, np.int32)
        topps = np.full(
            (nb,), 1.0 if req.top_p is None else req.top_p, np.float32)
        self._step_counter += 1
        first = np.asarray(self._first_sample(
            last_logits, jnp.asarray(temps), jnp.asarray(topks),
            jnp.asarray(topps), self._step_counter, self._base_key))
        req.slot_rows = rows
        for j, r in enumerate(rows):
            self._active[r] = True
            self._owner[r] = req
            self._aids[r] = req.adapter
            self._last_tok[r] = int(first[j])
            self._left[r] = req.budget - 1
            self._temps[r] = req.temp
            self._topks[r] = req.top_k if req.top_k else self.vocab
            self._topps[r] = 1.0 if req.top_p is None else req.top_p
            self._eos[r] = -1 if req.eos is None else int(req.eos)
            self._collected[r] = [int(first[j])]
            if self.speculate:
                # Drafting corpus: the row's real prompt (samples>1
                # shares the one prompt row) + the first token; every
                # emitted token appends, whichever path emitted it.
                src = 0 if req.samples > 1 else j
                self._spec_hist[r] = (
                    req.block[src, :int(req.lens[src])].tolist()
                    + [int(first[j])])
                self._spec_depth[r] = self.spec_gamma
        with self._lock:
            # A preempted continuation is the SAME request resuming,
            # not a new one (its first token is a mid-stream token).
            if not req.preempted_tokens:
                self._stats["requests"] += 1
            self._stats["tokens"] += len(rows)  # first sampled tokens
        if (self._obs is not None and req.trace is not None
                and not req.preempted_tokens):
            tr = req.trace
            # TTFT from ENQUEUE (the client-visible clock: queue wait +
            # prefill), not from admission.
            t0 = tr.t_enqueue
            ttft = time.perf_counter() - t0 if t0 is not None else 0.0
            self._obs.on_first_token(tr, ttft)
        if req.stream_q is not None:
            # First token per row streams immediately — it came from the
            # prefill's own logits, before any decode dispatch, so TTFT
            # is prefill latency, not prefill + a decode block.
            req.stream_q.put({j: [int(first[j])] for j in range(len(rows))})
        # eos on the very first token / budget 1 finishes immediately.
        for r in rows:
            if (self._left[r] <= 0
                    or (self._eos[r] >= 0
                        and self._last_tok[r] == self._eos[r])):
                self._finish_row(r)
        self._maybe_complete(req)

    def _finish_row(self, r: int) -> None:
        self._active[r] = False
        # Reset the slot's sampling temp: inactive rows still ride the
        # decode batch, and one stale temp>0 would disable the all-greedy
        # lax.cond fast path in _sample_rows for every later step until
        # the slot is reused.
        self._temps[r] = 0.0
        if self.speculate:
            self._spec_hist[r] = []  # corpus dies with the row
        if self.paged:
            # Session-end insert BEFORE the release below: the chain's
            # pages must be pinned while the row still holds its refs,
            # or the free list could hand them out in between.
            req = self._owner[r]
            if (req is not None and req.session is not None
                    and req.samples == 1 and req.block.shape[0] == 1
                    and self.prompt_cache > 0
                    and self._collected[r]):
                self._session_insert(req, r)
            # Free the row's pages NOW, not at request completion: the
            # zeroed table row sinks the slot's continued decode writes,
            # and shared prompt pages just drop a refcount — so a long
            # sibling can't hold a finished row's HBM hostage.
            self._release_slot_pages(r)

    def _fail_request(self, req: "_Request", err: Exception) -> None:
        for r in req.slot_rows:
            self._active[r] = False
            self._temps[r] = 0.0  # keep the all-greedy fast path alive
            self._owner[r] = None
            self._collected[r] = []
            if self.paged:
                self._release_slot_pages(r)
        req.error = err
        req.signal()

    def _expire_deadlines(self) -> None:
        """Free resources of requests whose client stopped waiting."""
        now = self._clock()
        n_expired = 0
        expired = [r for r in self._pending if now > r.deadline]
        for req in expired:
            self._pending.remove(req)
            req.error = TimeoutError("expired while queued")
            req.signal()
            n_expired += 1
        # The in-flight chunked admission too: its client may have given
        # up mid-prefill, and without this check the remaining chunks (and
        # the whole decode budget) would still run for nobody.
        if self._adm is not None and now > self._adm["req"].deadline:
            self._abort_admission(self._adm,
                                  TimeoutError("expired during admission"))
            n_expired += 1
        for req in {self._owner[r] for r in range(self.slots)
                    if self._owner[r] is not None}:
            if now > req.deadline:
                self._fail_request(
                    req, TimeoutError("expired while decoding"))
                n_expired += 1
        if n_expired:
            with self._lock:
                self._stats["deadline_expired"] += n_expired

    def _maybe_complete(self, req: "_Request") -> None:
        if any(self._active[r] for r in req.slot_rows):
            return
        pad_to = req.budget
        if self._obs is not None and req.trace is not None:
            tr = req.trace
            now = time.perf_counter()
            e2e = now - tr.t_enqueue if tr.t_enqueue is not None else 0.0
            # Mean time per output token after the first, over the
            # longest row (rows decode in lockstep, so the longest row's
            # clock is the request's decode clock). Computed BEFORE the
            # loop below clears the collected lists.
            ntok = min(max((len(self._collected[r])
                            for r in req.slot_rows), default=0), pad_to)
            tpot = ((now - tr.t_first) / (ntok - 1)
                    if tr.t_first is not None and ntok > 1 else None)
            self._obs.on_complete(tr, e2e, tpot)
        out = []
        for r in req.slot_rows:
            toks = self._collected[r][:pad_to]
            toks += [toks[-1]] * (pad_to - len(toks))  # eos-extend
            if req.preempted_tokens:
                # Loss-free preemption: the tokens emitted before the
                # park + the resumed tail = the ORIGINAL budget, one
                # uninterrupted greedy stream (tests/test_qos.py pins
                # bit-exactness against a never-preempted twin).
                toks = req.preempted_tokens + toks
            out.append(toks)
            self._owner[r] = None
            self._collected[r] = []
            if self.paged:
                self._release_slot_pages(r)  # no-op after _finish_row
        req.tokens = out
        req.signal()
