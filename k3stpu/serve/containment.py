"""Failure containment primitives for the serving stack (docs/RESILIENCE.md).

Three small pieces the engine and HTTP layer share:

- ``EngineStalled`` / ``CircuitOpen``: the retryable error types the
  containment layer raises instead of letting clients hang. Both map to
  HTTP 503 + ``Retry-After`` in the server, so a well-behaved client (or
  ``loadgen``'s backoff loop) retries against a replica that is healthy.

- ``CircuitBreaker``: classic closed -> open -> half-open breaker over
  *backend* failures (device dispatch / prefill exceptions — never client
  errors or deadline expiries). While open, admission rejects instantly
  and ``/healthz`` reports not-ready, so Kubernetes stops routing to the
  pod; after ``cooldown_s`` one probe request is let through (half-open)
  and its outcome decides whether the breaker closes or re-opens.

The breaker is deliberately time-function injectable and lock-cheap: the
``record_success`` fast path on a healthy engine is one attribute read.
"""

from __future__ import annotations

import threading
import time


class EngineStalled(RuntimeError):
    """The engine loop stopped making progress (watchdog trip or loop
    death). The request failed cleanly and is safe to retry."""


class CircuitOpen(RuntimeError):
    """Admission rejected because the circuit breaker is open after
    repeated backend failures. Retry after ``retry_after_s``."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


# Gauge encoding for /metrics (k3stpu_breaker_state).
_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Circuit breaker over consecutive backend failures.

    States:
      closed    — all traffic flows; ``threshold`` *consecutive* backend
                  failures trip it open.
      open      — admission rejects with ``CircuitOpen``; ``/healthz``
                  reports not-ready. After ``cooldown_s`` the next
                  ``allow()`` caller becomes the half-open probe.
      half_open — exactly one probe request in flight; success closes the
                  breaker, failure re-opens it. A probe lease older than
                  ``cooldown_s`` is considered lost (the probe's client
                  died without the request reaching a terminal record_*)
                  and a new probe is granted, so the breaker cannot wedge
                  itself half-open forever.
    """

    def __init__(self, threshold: int = 5, cooldown_s: float = 5.0,
                 time_fn=time.monotonic):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._now = time_fn
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0          # consecutive backend failures
        self._opened_at = 0.0          # when the breaker last opened
        self._probe_at: float | None = None   # outstanding probe lease
        self.trips = 0                 # total closed/half_open -> open

    # -- state ---------------------------------------------------------

    def _state_locked(self) -> str:
        """Current state with the time-based open -> half_open edge
        applied on read (so /healthz turns ready the moment a probe may
        flow, without waiting for a request to call allow())."""
        if (self._state == "open"
                and self._now() - self._opened_at >= self.cooldown_s):
            return "half_open"
        return self._state

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def state_value(self) -> int:
        return _STATE_VALUE[self.state()]

    def retry_after_s(self) -> float:
        """Seconds until a retry has a chance of being admitted."""
        with self._lock:
            if self._state != "open":
                return 1.0
            return max(0.1, self.cooldown_s - (self._now() - self._opened_at))

    # -- transitions ---------------------------------------------------

    def allow(self) -> "tuple[bool, bool]":
        """Admission gate. Returns ``(admitted, is_probe)``.

        Closed: ``(True, False)``. Open before cooldown: ``(False,
        False)``. At/after cooldown the caller is granted the half-open
        probe lease ``(True, True)`` — at most one outstanding lease per
        ``cooldown_s`` window.
        """
        with self._lock:
            if self._state == "closed":
                return True, False
            now = self._now()
            if self._state == "open":
                if now - self._opened_at < self.cooldown_s:
                    return False, False
                self._state = "half_open"
                self._probe_at = now
                return True, True
            # half_open: one probe at a time, but a lease older than
            # cooldown_s is presumed lost and replaced.
            if self._probe_at is not None and now - self._probe_at < self.cooldown_s:
                return False, False
            self._probe_at = now
            return True, True

    def probe_aborted(self) -> None:
        """The half-open probe never reached the backend (e.g. it lost
        the capacity race and got EngineOverloaded) — return the lease so
        the next caller can probe immediately."""
        with self._lock:
            if self._state == "half_open":
                self._probe_at = None

    def record_success(self) -> None:
        """A backend dispatch completed. Closes the breaker."""
        # Lock-free fast path for the healthy steady state.
        if self._state == "closed" and self._consecutive == 0:
            return
        with self._lock:
            self._consecutive = 0
            self._state = "closed"
            self._probe_at = None

    def record_failure(self) -> None:
        """A backend dispatch (or prefill/admission device call) failed."""
        with self._lock:
            self._consecutive += 1
            # The time-based edge may have moved open -> half_open without
            # any allow() call; honor it so a failure while probing
            # restarts the cooldown window.
            state = self._state_locked()
            if state == "half_open" or (
                    state == "closed" and self._consecutive >= self.threshold):
                self._trip_locked()

    def trip_open(self) -> None:
        """Force the breaker open (watchdog-detected stall)."""
        with self._lock:
            if self._state_locked() != "open":
                self._trip_locked()
            else:
                # Already open: restart the cooldown clock.
                self._opened_at = self._now()

    def _trip_locked(self) -> None:
        self._state = "open"
        self._opened_at = self._now()
        self._probe_at = None
        self.trips += 1
