"""KV-page manager: the engine's page pool, refcounts, prompt cache,
host tier, and block tables (docs/DISAGG.md names this layer in the
decomposed engine).

All mutation happens on the engine loop thread; HTTP threads marshal
operations through ``_TierCommand`` messages on the request queue.
``GenerateEngine`` composes this with the scheduler
(serve/scheduler.py) and model runner (serve/runner.py) as mixins over
one shared ``self`` — the decomposition moves code, not state, so the
bit-exactness suites pin behavior across the split.

This layer also owns the disaggregated-serving transfer primitives
(``export_chain`` / ``import_chain``): a prefill-role replica runs a
prompt's prefill into its prompt cache and serializes the finished
page chain in the ``HostPageStore`` wire format
(``tiering.encode_entry`` — crc32-checksummed, same leaf layout as
tier spills and drain park files); a decode-role replica restores the
bytes via one ``_restore_pages`` dispatch into a pinned prompt-cache
entry, so the request's admission there is an exact pcache hit and the
decode is bit-identical to a monolithic run."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import set_cache_index
from k3stpu.serve.programs import prompt_width_bucket
from k3stpu.serve.runner import _pow2_at_least
from k3stpu.serve.scheduler import _TierCommand
from k3stpu.serve.tiering import decode_entry, encode_entry, TierCorrupt


class _PageAllocator:
    """Host-side page bookkeeping for the paged KV cache (loop thread
    only). Page 0 is the reserved sink — pad rows and neutralized batch
    rows write there — so it is never handed out. Sharing (prompt-cache
    pins, sampled fan-outs) is refcounted: a page returns to the free
    list only when its last reference drops."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._rc = np.zeros((num_pages,), np.int32)
        self._free = list(range(num_pages - 1, 0, -1))  # pop() hands out 1 first

    @property
    def total(self) -> int:
        return self.num_pages - 1  # the sink page is not allocatable

    @property
    def free(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._rc[page])

    def alloc(self, n: int) -> "list[int] | None":
        """n fresh pages at refcount 1, or None (all-or-nothing)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._rc[pages] = 1
        return pages

    def incref(self, pages) -> None:
        for p in pages:
            if self._rc[p] <= 0:
                raise RuntimeError(f"incref on free page {p}")
            self._rc[p] += 1

    def decref(self, pages) -> None:
        for p in pages:
            if self._rc[p] <= 0:
                raise RuntimeError(f"double free of page {p}")
            self._rc[p] -= 1
            if self._rc[p] == 0:
                self._free.append(p)


class KVManagerMixin:
    """Prompt cache, page-chain bookkeeping, host tier, and the disagg
    KV-transfer primitives. Owns no state of its own — ``self`` is the
    composed ``GenerateEngine``."""

    # --- prompt cache (loop thread only; entries are immutable jax
    #     arrays, so a cached row survives the decodes of whatever slot
    #     its copy was scattered into) ------------------------------------

    def _pcache_lookup(self, prompt: tuple, adapter: int = 0):
        """Longest cached entry equal to ``prompt`` or a proper prefix of
        it, UNDER THE SAME ADAPTER (a row prefilled through adapter i's
        deltas is a different computation — cross-adapter reuse would be
        silently wrong); a hit refreshes its LRU position. Returns the
        PROMPT part of the key. Session-tail entries (logits slot None —
        the chain a finished session left behind covers prompt+reply
        K/V but no next-token distribution) only ever serve as PREFIX
        hits: an exact-length match would need the stored logits the
        entry doesn't have, so it is skipped and the shorter
        logits-bearing entry (or a miss) wins instead."""
        best = None
        for aid, key in self._pcache:
            if (aid == adapter and len(key) <= len(prompt)
                    and prompt[:len(key)] == key
                    and not (len(key) == len(prompt)
                             and self._pcache[(aid, key)][-2] is None)
                    and (best is None or len(key) > len(best))):
                best = key
        if best is None:
            return None, None
        entry = self._pcache.pop((adapter, best))  # re-insert at MRU
        self._pcache[(adapter, best)] = entry
        return best, entry

    def _pcache_insert(self, prompt: tuple, cache1, last1,
                       adapter: int = 0) -> None:
        if self.prompt_cache <= 0:
            return
        old = self._pcache.pop((adapter, prompt), None)
        nbytes = sum(x.nbytes for x in jax.tree.leaves((cache1, last1)))
        self._pcache[(adapter, prompt)] = (cache1, last1, nbytes)
        delta = nbytes - (old[2] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] = (
                self._stats.get("pcache_bytes", 0) + delta)

    def _pcache_extend(self, cache1, prompt: tuple, p0: int,
                       adapter: int = 0):
        """Append ``prompt[p0:]`` to a restored 1-row cache (row index sits
        at p0). Returns (cache, last_logits) in EXACTLY the post-prefill
        state: the suffix pads to a pow2 chunk, the index rolls back to
        len-1 (pad junk becomes invisible to the position mask, the
        chunked-admission finalize invariant) and the last real token is
        re-decoded in place for the exact first-token logits."""
        extra = np.asarray(prompt[p0:], np.int32)[None]
        g = _pow2_at_least(extra.shape[1])
        pad = np.zeros((1, g), np.int32)
        pad[:, :extra.shape[1]] = extra
        aids = self._aid_arg(1, adapter)
        cache = self._extend_chunk(self.params, cache1, jnp.asarray(pad),
                                   aids)
        cache = set_cache_index(
            cache, jnp.asarray([len(prompt) - 1], jnp.int32))
        return self._decode_logits(
            self.params, cache, jnp.asarray([prompt[-1]], jnp.int32), aids)

    # --- page-chain bookkeeping (paged mode; loop thread only) ----------

    def _pages_for(self, length: int, budget: int) -> int:
        return -(-(length + budget) // self.page_size)  # ceil div

    def _set_row(self, r: int, chain, index: int) -> None:
        self._chains[r] = list(chain)
        self._tables[r, :] = 0
        self._tables[r, :len(chain)] = chain
        self._indices[r] = index

    def _release_slot_pages(self, r: int) -> None:
        if self._chains[r]:
            self._alloc.decref(self._chains[r])
        self._chains[r] = []
        self._tables[r, :] = 0

    def _free_chains(self, chains) -> None:
        for c in chains or []:
            if c:
                self._alloc.decref(c)

    def _pages_needed(self, req, pkey) -> int:
        """Worst-case fresh pages this admission will allocate — the fit
        check, run BEFORE any device work or allocation. Mirrors the
        alloc paths exactly: cache hits only pay for non-shared pages."""
        ps, B = self.page_size, req.budget
        n = req.samples if req.samples > 1 else req.block.shape[0]
        # +1: a single-prompt admission pins a COW tail copy into the
        # prompt cache (the insert skips gracefully when the pool is
        # dry, but reserving it keeps the pin from stealing a page a
        # sibling row's chain already counted on).
        ins = 1 if (self.prompt_cache > 0
                    and req.block.shape[0] == 1) else 0
        if pkey is not None:
            L = len(req.ptuple())
            total = self._pages_for(L, B)
            if len(pkey) == L:  # exact hit: no insert afterwards
                return n * (total - len(pkey) // ps)
            # prefix: row 0 shares the entry, siblings share row 0
            return (total - len(pkey) // ps
                    + (n - 1) * (total - L // ps) + ins)
        if req.samples > 1:
            L = int(req.lens[0])
            total = self._pages_for(L, B)
            return total + (n - 1) * (total - L // ps) + ins
        return sum(self._pages_for(int(l), B)
                   for l in req.lens) + (ins if n == 1 else 0)

    def _alloc_request_chains(self, req, nb: int, n: int,
                              lens) -> "list[list[int]]":
        """Fresh page chains for a dense-prefilled admission, one list
        per real row (pad rows get []). samples>1 allocates the full
        chain for row 0 only — siblings get just their non-shared pages
        (install increfs the shared prefix into their chains)."""
        B = req.budget
        if self._chaos is not None:
            self._chaos.fire("page_alloc")
        if req.samples > 1:
            L = int(lens[0])
            total = self._pages_for(L, B)
            want = [total] + [total - L // self.page_size] * (n - 1)
        else:
            want = [self._pages_for(int(lens[j]), B) for j in range(n)]
        chains = []
        for w in want:
            c = self._alloc.alloc(w)
            if c is None:  # can't happen after the fit check; roll back
                self._free_chains(chains)
                raise RuntimeError("page pool exhausted mid-admission")
            chains.append(c)
        return chains + [[] for _ in range(nb - n)]

    def _pin_pages(self, chain) -> None:
        for p in chain:
            self._pinned[p] = self._pinned.get(p, 0) + 1

    def _unpin_pages(self, chain) -> None:
        for p in chain:
            left = self._pinned[p] - 1
            if left:
                self._pinned[p] = left
            else:
                del self._pinned[p]

    def _pcache_evict_lru(self, swap: bool = True) -> int:
        """Drop the LRU prompt-cache entry (paged entries release their
        page pins); returns its byte size. Caller adjusts the stat.
        With a host tier attached the entry's chain is GATHERED off
        device first (``swap=False`` skips that — crash paths where
        device state is untrusted), so eviction demotes instead of
        forgetting; a failed gather falls back to the plain drop."""
        key = next(iter(self._pcache))
        entry = self._pcache.pop(key)
        if self.paged:
            if swap and self._tier is not None:
                self._tier_swap_out(key, entry)
            self._unpin_pages(entry[0])
            self._alloc.decref(entry[0])
        return entry[-1]

    def _pcache_insert_paged(self, prompt: tuple, src_chain, last1,
                             adapter: int = 0,
                             frozen: bool = False) -> None:
        """Pin ``prompt``'s pages into the prompt cache WITHOUT copying
        the prompt K/V: the entry shares the source row's full pages by
        incref — safe read-only, since a row only ever writes positions
        >= its admitted length, which live past its full prompt pages —
        and copies only the partial tail page (the row's next decode
        DOES write into that one). Skipped when the pool can't spare
        the tail copy.

        ``frozen``: the source row is FINISHED (session-end insert) —
        nothing will ever write its tail page again, so the partial
        tail is shared by incref like the full pages instead of COW
        copied (a later admission that extends the entry takes its own
        tail copy through ``build_row``, same as any prefix hit). Saves
        one page + one device copy per session turn, and cannot fail on
        an exhausted pool."""
        if self.prompt_cache <= 0:
            return
        ps = self.page_size
        full = len(prompt) // ps
        chain = list(src_chain[:full])
        self._alloc.incref(chain)
        if len(prompt) % ps:
            if frozen:
                chain.append(src_chain[full])
                self._alloc.incref(chain[-1:])
            else:
                tail = self._alloc.alloc(1)
                if tail is None:
                    self._alloc.decref(chain)
                    return  # pool too tight to pin a copy — skip caching
                self._cache = self._copy_page(self._cache,
                                              src_chain[full], tail[0])
                chain.append(tail[0])
        old = self._pcache.pop((adapter, prompt), None)
        if old is not None:
            self._unpin_pages(old[0])
            self._alloc.decref(old[0])
        self._pin_pages(chain)
        nbytes = len(chain) * self._page_bytes \
            + (sum(x.nbytes for x in jax.tree.leaves(last1))
               if last1 is not None else 0)
        self._pcache[(adapter, prompt)] = (tuple(chain), len(prompt),
                                           last1, nbytes)
        delta = nbytes - (old[-1] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] += delta

    # --- host page tier (docs/TIERING.md; loop thread only) -------------

    def _gather_pages(self, chain) -> dict:
        """One host copy of a page chain: every ``*_pages`` pool leaf
        gathered at the chain's indices, fetched in a SINGLE
        ``jax.device_get`` of the whole dict (one transfer round-trip,
        not one per layer). Keys are the "/"-joined leaf paths —
        exactly what ``_restore_pages`` scatters back from.

        This is also what makes the tier/disagg wire format
        shard-count-AGNOSTIC under tensor parallelism: on a TP engine
        each pool leaf is sharded on its head axis, and ``device_get``
        assembles the full head-axis-concat array on the host — the
        exported bytes are identical whatever ``tp_shards`` produced
        them. The import side's jitted ``_restore_pages`` scatter then
        re-splits per the DESTINATION engine's sharding, so a 2-shard
        prefill replica can hand off to a 1-shard decode replica (or
        vice versa) bit-exact (docs/DISAGG.md "TP × disagg")."""
        idx = jnp.asarray(chain, jnp.int32)
        out = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self._cache)[0]:
            if str(getattr(path[-1], "key", "")).endswith("_pages"):
                key = "/".join(str(getattr(k, "key", k)) for k in path)
                out[key] = leaf[idx]
        return jax.device_get(out)

    def _install_host_chain(self, key, length: int, host: dict,
                            last) -> bool:
        """Install a host-gathered chain as a pinned prompt-cache entry
        — the shared tail of tier swap-in and disagg KV import. FRESH
        pages only: no live row's table points at them, so any failure
        rolls back by freeing them — live rows are untouchable by
        construction. Allocates (pressure-evicting idle pcache entries
        first), scatters the host buffers in via one ``_restore_pages``
        dispatch, pins + inserts — after which the entry serves hits
        exactly like one that never left HBM. Returns False when the
        pool is too tight even after pressure; raises when the restore
        dispatch itself fails (caller degrades to cold prefill)."""
        n = -(-length // self.page_size)
        while n > self._alloc.free and self._pcache:
            freed = self._pcache_evict_lru()
            with self._lock:
                self._stats["pcache_bytes"] -= freed
        pages = self._alloc.alloc(n)
        if pages is None:
            return False
        try:
            npad = _pow2_at_least(n)
            idx = np.zeros((npad,), np.int32)
            idx[:n] = pages
            hpad = {}
            for k, v in host.items():
                buf = np.zeros((npad,) + v.shape[1:], v.dtype)
                buf[:n] = v[:n]
                hpad[k] = buf
            self._cache = self._restore_pages(self._cache, hpad,
                                              jnp.asarray(idx))
            last_dev = jnp.asarray(last) if last is not None else None
        except Exception:  # noqa: BLE001 — restore dispatch failed
            self._alloc.decref(pages)
            raise
        self._pin_pages(pages)
        old = self._pcache.pop(key, None)
        if old is not None:  # raced a fresh insert; replace it
            self._unpin_pages(old[0])
            self._alloc.decref(old[0])
        nbytes = n * self._page_bytes \
            + (int(last_dev.nbytes) if last_dev is not None else 0)
        self._pcache[key] = (tuple(pages), length, last_dev, nbytes)
        delta = nbytes - (old[-1] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] += delta
        return True

    def _tier_swap_out(self, key, entry) -> bool:
        """Gather a pcache entry's chain to the host tier. The caller
        still owns the entry (and drops its pins/refs afterwards) —
        this only copies bytes off device, so a failure (chaos
        ``tier_swap``, host OOM) simply leaves the entry to die the
        pre-tier way: dropped, next turn pays a cold prefill. Entry
        pages are immutable once inserted (COW discipline), so the
        gather needs no quiescence even while live rows share the
        chain's full pages."""
        t0 = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("tier_swap")
            host = self._gather_pages(entry[0])
            last = entry[2]
            if last is not None:
                last = jax.device_get(last)
            self._tier.put(key, entry[1], host, last=last)
        except Exception:  # noqa: BLE001 — degrade to plain eviction
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["tier_swap_outs"] += 1
        if self._obs is not None:
            self._obs.on_tier_swap(
                "out", dt, self._tier.stats()["tier_pages"],
                self._alloc.total - self._alloc.free)
        return True

    def _tier_swap_in(self, key) -> bool:
        """Restore a tier entry into the prompt cache via
        ``_install_host_chain`` — after which the entry serves hits
        exactly like one that never left. Failure paths degrade to a
        cold prefill (``tier_fallbacks``); corrupt/undecodable entries
        are discarded so they cannot fail every later probe too."""
        t0 = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("tier_swap")
            length, host, last = self._tier.load(key)
        except Exception:  # noqa: BLE001 — torn spill / injected fault
            self._tier.discard(key)
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        try:
            installed = self._install_host_chain(key, length, host, last)
        except Exception:  # noqa: BLE001 — restore dispatch failed
            self._record_backend_failure()
            self._tier.discard(key)
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        if not installed:
            # Pool too tight even after pressure: keep the host copy
            # (it is still good — a later, calmer admission can restore
            # it) and let THIS request prefill cold.
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        with self._lock:
            self._stats["tier_swap_ins"] += 1
        self._tier.discard(key)  # moved, not copied: one owner at a time
        if self._obs is not None:
            self._obs.on_tier_swap(
                "in", time.perf_counter() - t0,
                self._tier.stats()["tier_pages"],
                self._alloc.total - self._alloc.free)
        return True

    def _tier_pressure(self) -> None:
        """Low-watermark demotion, run once per loop iteration: while
        the free list sits below ``tier_watermark`` and idle pcache
        entries exist, gather the LRU entry to host and return its
        pages. Terminates because each pass shrinks the pcache;
        entries whose pages are shared with live rows free only their
        unshared pages (refcounts), which is exactly the reclaimable
        amount."""
        while (self._alloc.free < self.tier_watermark and self._pcache):
            freed = self._pcache_evict_lru()
            with self._lock:
                self._stats["pcache_bytes"] -= freed

    def _session_insert(self, req, r: int) -> None:
        """Session-end insert (called from _finish_row BEFORE the row's
        pages are released): pin the finished row's chain into the
        prompt cache keyed by prompt + every reply token except the
        last. That key is exactly the K/V the chain holds — after g
        emitted tokens the row's index is L+g-1 and positions
        L..L+g-2 hold t1..t_{g-1}; the last sampled token's K/V was
        never written (and any mid-block post-eos junk lies beyond the
        key length, invisible to the position mask). The entry stores
        last=None — no logits exist for the uncommitted tail token —
        so it serves prefix hits only (the next turn's prompt strictly
        extends it through t_g). The session's previous chain is
        dropped from pcache AND tier: one chain per session. A
        one-token turn adopts the admission-time exact-prompt entry
        (same key, better: it has logits) rather than inserting."""
        toks = self._collected[r]
        if len(toks) < 2:
            # One-token turn: the key (prompt + zero committed reply
            # tokens) IS the prompt, and admission already cached that
            # exact chain WITH its next-token logits. Inserting a
            # frozen last=None twin would replace the strictly better
            # entry — adopt the existing one into the ledger instead,
            # so release_session parks the live chain, not the
            # previous turn's stale key.
            key = (req.adapter, req.ptuple())
            if key not in self._pcache:
                return  # evicted (or never inserted); keep prev chain
        else:
            key_prompt = req.ptuple() + tuple(toks[:-1])
            n_entry = -(-len(key_prompt) // self.page_size)
            chain = self._chains[r]
            if len(chain) < n_entry:  # defensive: never by allocation
                return
            self._pcache_insert_paged(key_prompt, chain[:n_entry], None,
                                      req.adapter, frozen=True)
            key = (req.adapter, key_prompt)
            if key not in self._pcache:
                return  # capacity-evicted immediately; nothing to track
        prev = self._sessions.get(req.session)
        if prev is not None and prev != key:
            ent = self._pcache.pop(prev, None)
            if ent is not None:
                self._unpin_pages(ent[0])
                self._alloc.decref(ent[0])
                with self._lock:
                    self._stats["pcache_bytes"] -= ent[-1]
            if self._tier is not None:
                self._tier.discard(prev)
        self._sessions[req.session] = key

    def _do_release_session(self, session: str,
                            spill: bool = False) -> bool:
        """Loop-thread body of release_session: demote the session's
        pcache entry to the host tier (gather + unpin + free pages).
        True when a chain existed (now on host — or already there).
        ``spill`` additionally forces the parked chain to the disk tier
        (no-op without --tier-dir): the drain path, where the chain
        must outlive this process for a peer replica to adopt it."""
        key = self._sessions.get(session)
        if key is None:
            return False
        entry = self._pcache.pop(key, None)
        if entry is None:
            # Already demoted (watermark pressure / LRU eviction beat
            # the explicit release to it).
            had = self._tier is not None and self._tier.contains(key)
            if had and spill:
                self._tier.spill(key)
            return had
        if self._tier is not None:
            if self._tier_swap_out(key, entry) and spill:
                self._tier.spill(key)
        self._unpin_pages(entry[0])
        self._alloc.decref(entry[0])
        with self._lock:
            self._stats["pcache_bytes"] -= entry[-1]
        return True

    def release_session(self, session: str,
                        timeout_s: float = 30.0,
                        spill: bool = False) -> bool:
        """Explicitly park a session between turns: its cached chain
        leaves the device pool for the host tier (or is dropped when no
        tier is attached) and the freed pages go back to admission.
        ``spill=True`` forces the parked chain through to the disk tier
        so it survives this process (drain-before-kill; requires
        --tier-dir to have any effect). Safe from any thread — the
        operation marshals to the loop thread via the request queue.
        Returns whether the session had a chain to release."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not self.paged:
            return False
        cmd = _TierCommand("release", session, spill=spill)
        self._q.put(cmd)
        if not cmd.event.wait(timeout_s):
            raise TimeoutError("session release did not finish in time")
        if cmd.error is not None:
            raise cmd.error
        return bool(cmd.result)

    # --- disagg KV transfer (docs/DISAGG.md; loop-thread bodies) --------

    def note_transfer_fallback(self) -> None:
        """Count one degraded KV handoff (torn/checksum-failed transfer,
        unreachable prefill peer, pool too tight to install): the
        request still completes via a cold prefill on this replica —
        this only records that the fast path was lost. Callable from
        any thread (the server's HTTP-failure path uses it too)."""
        with self._lock:
            self._stats["transfer_fallbacks"] += 1
        if self._obs is not None:
            self._obs.on_transfer_fallback()

    def _prefill_into_pcache(self, prompt: tuple, adapter: int) -> None:
        """Prefill-role primitive: run ``prompt``'s prefill into a fresh
        page chain and pin it as an exact prompt-cache entry WITH its
        next-token logits — the same dense-prefill + ``_pack_pages``
        pipeline a monolithic admission runs, minus any decode rows, so
        the entry's bytes are identical to what a monolithic admission
        would have pinned. The export owns the whole chain (no live row
        shares it), so the insert pins directly without the COW tail
        copy ``_pcache_insert_paged`` pays."""
        L = len(prompt)
        n = -(-L // self.page_size)
        while n > self._alloc.free and self._pcache:
            freed = self._pcache_evict_lru()
            with self._lock:
                self._stats["pcache_bytes"] -= freed
        chain = self._alloc.alloc(n)
        if chain is None:
            raise RuntimeError(
                f"prefill export needs {n} pages but only "
                f"{self._alloc.free} are free")
        try:
            width = prompt_width_bucket(L, self.max_seq)
            block = np.zeros((1, width), np.int32)
            block[0, :L] = prompt
            small, last = self._prefill(
                self.params, jnp.asarray(block),
                jnp.asarray([L], np.int32), self._aid_arg(1, adapter))
            pm = np.zeros((1, self.n_bt), np.int32)
            pm[0, :n] = chain
            self._cache = self._pack_pages(self._cache, small,
                                           jnp.asarray(pm))
        except Exception:  # noqa: BLE001 — roll back, caller degrades
            self._record_backend_failure()
            self._alloc.decref(chain)
            raise
        old = self._pcache.pop((adapter, prompt), None)
        if old is not None:
            self._unpin_pages(old[0])
            self._alloc.decref(old[0])
        self._pin_pages(chain)
        nbytes = n * self._page_bytes \
            + sum(int(x.nbytes) for x in jax.tree.leaves(last))
        self._pcache[(adapter, prompt)] = (tuple(chain), L, last, nbytes)
        delta = nbytes - (old[-1] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] += delta

    def _do_export_chain(self, prompt: tuple, adapter: int) -> bytes:
        """Loop-thread body of export_chain: stage the prompt's finished
        prefill in the prompt cache (an exact repeat reuses the staged
        entry — the prefill replica's own prompt cache makes repeated
        exports free), gather the chain off device, and serialize it in
        the tier wire format. Chaos ``kv_transfer`` fires first: an
        injected fault fails THIS export cleanly (the decode peer
        degrades to cold prefill), loop alive."""
        t0 = time.perf_counter()
        if self._chaos is not None:
            self._chaos.fire("kv_transfer")
        key = (adapter, prompt)
        entry = self._pcache.get(key)
        if entry is None or entry[2] is None:
            # Miss (or a logits-less session tail an exact export can't
            # use): run the prefill now.
            self._prefill_into_pcache(prompt, adapter)
            entry = self._pcache.get(key)
            if entry is None or entry[2] is None:
                raise RuntimeError("prefill export: cache insert failed")
        else:
            self._pcache[key] = self._pcache.pop(key)  # MRU refresh
        host = self._gather_pages(entry[0])
        last = jax.device_get(entry[2])
        data = encode_entry(key, entry[1], host, last)
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["kv_exports"] += 1
            self._stats["kv_transfer_bytes"] += len(data)
        if self._obs is not None:
            self._obs.on_kv_transfer("export", dt, len(data))
        return data

    def _do_import_chain(self, data: bytes) -> bool:
        """Loop-thread body of import_chain: checksum-verify the wire
        bytes and install the chain as a pinned prompt-cache entry via
        one ``_restore_pages`` dispatch — the next admission of that
        prompt is then an exact pcache hit, bit-identical to a
        monolithic run. EVERY failure (chaos ``kv_transfer``, torn or
        checksum-failed payload, restore-dispatch error, pool too
        tight) returns False with ``transfer_fallbacks`` counted — the
        caller just submits normally and pays a cold prefill; live rows
        are untouchable because only fresh pages were ever involved."""
        t0 = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("kv_transfer")
            key, length, host, last = decode_entry(bytes(data))
            adapter, prompt = key
            if (not isinstance(prompt, tuple) or not isinstance(host, dict)
                    or length != len(prompt) or length < 1
                    or length > self.max_seq):
                raise TierCorrupt("transfer payload malformed")
        except Exception:  # noqa: BLE001 — torn transfer / injected fault
            self.note_transfer_fallback()
            return False
        try:
            installed = self._install_host_chain(key, length, host, last)
        except Exception:  # noqa: BLE001 — restore dispatch failed
            self._record_backend_failure()
            self.note_transfer_fallback()
            return False
        if not installed:
            self.note_transfer_fallback()
            return False
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["kv_imports"] += 1
            self._stats["kv_transfer_bytes"] += len(data)
        if self._obs is not None:
            self._obs.on_kv_transfer("import", dt, len(data))
        return True

    def export_chain(self, prompt, *, adapter_id: int = 0,
                     timeout_s: float = 60.0) -> bytes:
        """Prefill-role API: run ``prompt``'s prefill (or reuse this
        replica's cached one) and return its finished page chain +
        next-token logits serialized in the checksummed tier wire
        format — the unit a decode-role replica restores with
        ``import_chain``. The wire format is shard-count-agnostic:
        ``_gather_pages`` assembles sharded pool leaves to full
        head-axis-concat host arrays, so the exporter's ``tp_shards``
        never leaks into the bytes. Safe from any thread (marshals to
        the loop thread); raises on any failure so the HTTP layer can
        signal the decode peer to fall back to a cold prefill."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not self.paged:
            raise ValueError("KV export requires paged mode (page_size)")
        if self.prompt_cache <= 0:
            raise ValueError("KV export requires prompt_cache > 0 (the "
                             "exported chain is staged there)")
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if len(prompt) > self.max_seq:
            raise ValueError(f"prompt {len(prompt)} exceeds the cache "
                             f"({self.max_seq})")
        adapter_id = int(adapter_id)
        if adapter_id != 0 and self.n_adapters is None:
            raise ValueError("this engine's model has no adapter stacks "
                             "(multi_lora is off); adapter_id must be 0")
        if self.n_adapters is not None \
                and not 0 <= adapter_id < self.n_adapters:
            raise ValueError(f"adapter_id {adapter_id} outside "
                             f"[0, {self.n_adapters})")
        n = -(-len(prompt) // self.page_size)
        if n > self._alloc.total:
            raise ValueError(
                f"prompt needs {n} pages but the pool has "
                f"{self._alloc.total} usable")
        cmd = _TierCommand("export", "", payload=(prompt, adapter_id))
        self._q.put(cmd)
        if not cmd.event.wait(timeout_s):
            raise TimeoutError("KV export did not finish in time")
        if cmd.error is not None:
            raise cmd.error
        return cmd.result

    def import_chain(self, data: bytes, *,
                     timeout_s: float = 60.0) -> bool:
        """Decode-role API: restore a chain exported by a prefill-role
        peer into this engine's prompt cache. The peer may run a
        different ``tp_shards`` — the wire carries full head-axis
        arrays and the restore scatter re-splits them per THIS
        engine's sharding. Returns True when the
        next admission of that prompt will be an exact pcache hit;
        False when the transfer was torn/corrupt or could not be
        installed (``transfer_fallbacks`` counted — just submit
        normally and pay a cold prefill). Safe from any thread."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not self.paged:
            raise ValueError("KV import requires paged mode (page_size)")
        if self.prompt_cache <= 0:
            raise ValueError("KV import requires prompt_cache > 0 (the "
                             "restored chain lands there)")
        cmd = _TierCommand("import", "", payload=bytes(data))
        self._q.put(cmd)
        if not cmd.event.wait(timeout_s):
            raise TimeoutError("KV import did not finish in time")
        if cmd.error is not None:
            raise cmd.error
        return bool(cmd.result)

    def _exec_tier_command(self, cmd: "_TierCommand") -> None:
        try:
            if cmd.kind == "release":
                cmd.result = self._do_release_session(cmd.session,
                                                      spill=cmd.spill)
            elif cmd.kind == "export":
                cmd.result = self._do_export_chain(*cmd.payload)
            elif cmd.kind == "import":
                cmd.result = self._do_import_chain(cmd.payload)
            else:  # unknown kinds fail loudly, never hang the caller
                raise ValueError(f"unknown tier command {cmd.kind!r}")
        except Exception as e:  # noqa: BLE001 — fail the one command
            cmd.error = e
        cmd.signal()
