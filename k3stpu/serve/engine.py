"""Continuous batching for LM generation — slot-based decode scheduling.

``generate_tokens`` runs whole requests back-to-back: a 256-token
generation holds the chip while later requests queue, and a batch-1
request decodes alone at batch-1 arithmetic intensity. This engine is the
TPU-native fix (the serving pattern vLLM/Orca made standard, built here on
XLA-static shapes):

- ONE decode program, compiled once, over a fixed block of ``slots`` cache
  rows. Every step advances all active slots together; per-row cache
  indices (models/transformer.py) let rows sit at different depths.
- Requests JOIN mid-flight: a free slot gets the new request's prefilled
  cache rows scattered in between decode steps; finished slots free
  immediately. No request waits for another to finish, and decode batch
  density — the thing MXU throughput scales with — stays high under load.
- Everything device-side is shape-static: prefill widths and admitted-row
  counts come from small power-of-two bucket sets, so steady state runs a
  handful of compiled programs, never a recompile.
- Per-slot sampling params travel as traced (B,) arrays (temperature,
  top-k, eos), so heterogeneous requests share the one decode program.

The reference has no serving scheduler at all (its workload is a stock
binary behind a Service, reference jellyfin.yaml:1-43); this is the
match-or-beat half of the serving story.

The engine is composed from three layers over one shared ``self``
(their state is disjoint and every method runs against the same
object, so the split moves code, not behavior — pinned by the
bit-exactness suites):

- ``serve/scheduler.py`` — admission, chunked-prefill budgeting, the
  continuous-batching policy, and the client-facing submit paths.
- ``serve/kv_manager.py`` — page pool + refcounts, prompt cache, host
  tier, block tables, and the disagg KV-transfer primitives
  (``export_chain`` / ``import_chain``, docs/DISAGG.md).
- ``serve/runner.py`` — the jitted prefill/decode/spec-verify device
  programs.

This module keeps the loop thread itself (plus crash containment and
the watchdog) and re-exports the public surface, so
``from k3stpu.serve.engine import GenerateEngine`` keeps working.
"""

from __future__ import annotations

import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import init_cache, paged_model
from k3stpu.serve.containment import EngineStalled
from k3stpu.serve.kv_manager import KVManagerMixin, _PageAllocator
from k3stpu.serve.runner import (
    ModelRunnerMixin,
    _pow2_at_least,
    _sample_rows,
)
from k3stpu.serve.scheduler import (
    QOS_CLASSES,
    AdmissionRejected,
    EngineOverloaded,
    SchedulerMixin,
    _Request,
    _TierCommand,
)

__all__ = [
    "GenerateEngine",
    "AdmissionRejected",
    "EngineOverloaded",
    "QOS_CLASSES",
    "_PageAllocator",
    "_Request",
    "_TierCommand",
    "_pow2_at_least",
    "_sample_rows",
]


class GenerateEngine(SchedulerMixin, KVManagerMixin, ModelRunnerMixin):
    """Owns a ``slots``-row KV cache and a single decode loop thread.

    ``submit()`` blocks the calling (HTTP handler) thread until its
    request's rows finish; the loop thread interleaves every live request
    into one decode batch. ``close()`` drains and stops the thread.
    """

    def __init__(self, model, params, *, slots: int = 8,
                 seed: int = 0, chunk_prefill: "int | None" = None,
                 decode_block: int = 1, prompt_cache: int = 0,
                 mesh=None, tp_shards: int = 1,
                 max_pending: "int | None" = None,
                 page_size: "int | None" = None,
                 num_pages: "int | None" = None,
                 attn_backend: str = "xla-gather",
                 speculate: bool = False, spec_gamma: int = 4,
                 obs=None,
                 breaker=None, watchdog_s: "float | None" = None,
                 chaos=None, tier=None, tier_watermark: int = 0,
                 qos: bool = False,
                 interactive_ttft_slo_s: "float | None" = 2.5,
                 batch_ttft_slo_s: "float | None" = 30.0,
                 clock=time.time):
        """``chunk_prefill``: admit long prompts in chunks of this many
        tokens, one chunk per loop iteration — bounds how long a decode
        step can be delayed by an arriving prompt to one chunk's latency
        instead of the whole prompt's. None = single-shot admission.

        ``decode_block``: decode this many tokens per device dispatch
        (an inner ``lax.scan``), host-side eos/budget/deadline checks in
        between blocks. Through a relayed backend each dispatch costs
        ~8 ms regardless of work, capping a per-token loop at ~125
        steps/s; a K-token block amortizes that floor K-fold. Trade-off:
        a new request joins on a block boundary (K-token granularity),
        and a row that hits eos mid-block rides out the rest of the
        block with its surplus tokens discarded host-side.

        ``prompt_cache``: keep up to this many prefilled single-prompt
        KV rows (LRU) keyed by the exact prompt tokens. A repeat prompt
        skips its prefill entirely; a prompt that EXTENDS a cached one
        restores the row and appends only the new tokens (the chat /
        shared-system-prompt pattern — prefill cost drops from O(whole
        prompt) to O(new suffix)). Cost: one full-depth cache row of
        HBM per entry (``stats()['pcache_bytes']``). Outputs are
        bit-identical to the uncached path: the restored row IS the
        prefilled row (jax arrays are immutable, so a cached row can't
        be corrupted by the decodes of the slot it was scattered into),
        and the suffix-append reuses the chunked-admission finalize
        invariant (junk K/V beyond a row's index is invisible to the
        position mask and gets overwritten slot-by-slot). 0 disables.

        ``mesh``: tensor-parallel serving over a jax Mesh with a
        'model' axis (parallel/mesh.make_mesh's convention — required).
        The params arrive sharded over that axis
        (parallel/sharding.py); the KV cache must live on the SAME
        devices or jit refuses the mixed placement, so it goes up
        sharded on its kv-head axis where divisible (attention splits
        by head under TP) and replicated otherwise. Host-side numpy
        inputs stay uncommitted — jit places them. None =
        single-device (programs unchanged).

        ``tp_shards``: tensor-parallel shard count — the serving twin
        of the training side's model parallelism (--tp-shards on the
        server). ``1`` (the default) is byte-identical to the pre-TP
        engine: no mesh is built and every program traces exactly as
        before. ``N > 1`` with no explicit ``mesh`` builds a pure-TP
        mesh over the first N local devices and shards ``params``
        itself (parallel/sharding.shard_params); with an explicit
        ``mesh`` the counts must agree. Attention-head divisibility is
        validated up front (the KV pool partitions on the head axis —
        per-shard page pools behind ONE shared block table, so the
        allocator, COW sharing, and chain export/import are all
        shard-count-agnostic).

        ``page_size`` / ``num_pages``: PAGED KV cache. The decode cache
        becomes one pool of ``num_pages`` fixed pages per layer instead
        of ``slots`` monolithic ``max_seq``-deep rows; each slot holds a
        chain of just ``ceil((len + budget) / page_size)`` pages,
        addressed through a traced block table — so admission is bounded
        by FREE PAGES, not free rows, and the same HBM serves far more
        concurrent short requests (``stats()['paged_density_ratio']``).
        ``num_pages`` defaults to the dense footprint + the sink page;
        set it LOWER to realize the density win. The prompt cache
        upgrades to zero-copy prefix sharing: entries pin their pages
        (refcounted, read-only) into admitted rows' tables instead of
        copying whole cache rows; only a partial tail page is copied
        (the row writes into it). Token streams stay bit-identical to
        the dense engine's. None = dense cache (everything unchanged).

        ``attn_backend``: how the paged decode/extend path reads the KV
        pool (cfg.attn_backend doc in models/transformer.py).
        ``"xla-gather"`` (default) materializes gathered pages in XLA;
        ``"pallas-paged"`` walks block tables inside the fused Pallas
        kernel (ops/paged_attention.py) — token-identical under greedy
        decoding, no gather materialization. Requires paged mode; off
        TPU the kernel runs in interpreter mode (slow — tests only).

        ``speculate`` / ``spec_gamma``: draft-then-verify speculative
        decoding inside the slot loop (paged mode only — the host
        index mirror is what makes per-row rollback free). Each
        iteration an ``NgramDrafter`` (serve/speculative.py) proposes
        up to ``spec_gamma`` continuation tokens per active row from
        the row's own prompt+generated history; one batch-wide verify
        dispatch (a static ``(slots, spec_gamma+1)`` extend — one
        compile, zero steady-state recompiles) scores every proposal,
        and each row emits its matched prefix plus the target's own
        token at the first divergence — up to ``spec_gamma + 1``
        tokens per dispatch instead of ``decode_block`` device steps'
        worth. Greedy verification means output stays token-identical
        to the non-speculative engine and to ``generate()``; rejected
        proposals roll back for free through the host index mirror.
        Per-slot speculation depth adapts to recent acceptance (full
        accept grows it toward ``spec_gamma``, full reject shrinks it
        toward 1) so rows whose continuation stopped repeating stop
        paying draft+verify for doomed proposals. Iterations where no
        row has a proposal — or any row samples (temperature > 0), or
        a row sits within ``spec_gamma`` tokens of ``max_seq_len`` —
        fall through to the plain decode path unchanged, which is why
        non-repetitive traffic keeps plain-path throughput.

        ``obs``: a ``k3stpu.obs.ServeObs`` to record per-request
        lifecycle traces and latency histograms into (the server shares
        one instance so /metrics and /debug/* see engine traffic).
        None = no recording, zero overhead on every path.

        ``breaker``: a ``containment.CircuitBreaker``. Backend dispatch
        failures feed it; while open, admission raises ``CircuitOpen``
        (HTTP 503 + Retry-After, ``/healthz`` not-ready) until a
        half-open probe request succeeds. None = no breaker.

        ``watchdog_s``: start a watchdog thread that fails in-flight
        requests with retryable ``EngineStalled`` errors when the loop
        makes no progress for this many seconds (a wedged backend
        dispatch), and revives the loop thread if it dies. Must exceed
        the worst-case single dispatch (including cold compiles). None =
        no watchdog (the library default; the HTTP server turns it on).

        ``chaos``: a ``k3stpu.chaos.FaultInjector`` consulted at the
        loop/dispatch/allocator fault boundaries. None (the default) =
        no injection, zero overhead — production paths never arm this.

        ``tier`` / ``tier_watermark``: host-memory KV page tier
        (``serve/tiering.HostPageStore`` — paged mode + prompt_cache
        only). Prompt-cache evictions GATHER their page chains to host
        RAM instead of dropping them; the admission probe checks the
        tier before declaring a pcache miss and restores a match into
        fresh pages (one batched device_put + scatter), token-identical
        to a never-swapped run. When ``tier_watermark`` > 0 the loop
        proactively swaps out LRU pcache entries whenever
        ``pages_free`` sits below it, so HBM pressure converts idle
        sessions into host bytes instead of admission stalls. A failed
        swap-in (chaos ``tier_swap``, torn disk spill) degrades to a
        cold prefill — counted in ``tier_fallbacks``, live rows
        untouched. ``release_session(sid)`` force-evicts a session's
        chain to the tier between turns (docs/TIERING.md).

        ``qos``: SLO-aware priority classes (docs/QOS.md). Requests
        carry ``priority`` ("interactive"/"batch"); admission walks
        interactive first and splits the chunked-prefill token budget
        between the classes; predictive admission control rejects a
        request up front (``AdmissionRejected`` → 503 + Retry-After)
        when the TTFT forecast breaches its class SLO; and — on a
        paged engine with a ``tier`` — an interactive request that
        cannot be admitted preempts a running batch request by parking
        its KV chain + generation state on the tier, loss-free: the
        victim resumes token-identically. False (the default) is
        byte-identical to the classless engine.

        ``interactive_ttft_slo_s`` / ``batch_ttft_slo_s``: per-class
        TTFT SLOs the predictive gate enforces (None or <= 0 disables
        the gate for that class). Defaults match
        ``k3stpu.obs.slo.qos_specs``."""
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if mesh is not None and "model" not in mesh.shape:
            raise ValueError(
                f"engine mesh needs a 'model' axis, got {mesh.shape}")
        if tp_shards < 1:
            raise ValueError(f"tp_shards must be >= 1, got {tp_shards}")
        if (mesh is not None and tp_shards > 1
                and int(mesh.shape["model"]) != tp_shards):
            raise ValueError(
                f"tp_shards={tp_shards} disagrees with the mesh's "
                f"'model' axis ({mesh.shape['model']})")
        if tp_shards > 1:
            cfg_ = getattr(model.config, "base", model.config)
            kvh = cfg_.n_kv_heads or cfg_.n_heads
            if cfg_.n_heads % tp_shards or kvh % tp_shards:
                raise ValueError(
                    f"tp_shards={tp_shards} must divide the attention "
                    f"heads (q={cfg_.n_heads}, kv={kvh}) — the KV pool "
                    f"partitions on the head axis")
            if mesh is None:
                n_dev = len(jax.devices())
                if n_dev < tp_shards:
                    raise ValueError(
                        f"tp_shards={tp_shards} needs that many devices, "
                        f"have {n_dev}")
                from k3stpu.parallel.mesh import make_mesh
                from k3stpu.parallel.sharding import shard_params

                mesh = make_mesh(tp_shards, model_parallelism=tp_shards)
                params, _ = shard_params(params, mesh)
        if chunk_prefill is not None and chunk_prefill < 1:
            raise ValueError(f"chunk_prefill must be >= 1, got "
                             f"{chunk_prefill}")
        if decode_block < 1:
            raise ValueError(f"decode_block must be >= 1, got "
                             f"{decode_block}")
        if prompt_cache < 0:
            raise ValueError(f"prompt_cache must be >= 0, got "
                             f"{prompt_cache}")
        if watchdog_s is not None and watchdog_s <= 0:
            raise ValueError(f"watchdog_s must be > 0, got {watchdog_s}")
        from k3stpu.models.transformer import ATTN_BACKENDS
        if attn_backend not in ATTN_BACKENDS:
            raise ValueError(f"attn_backend {attn_backend!r} not in "
                             f"{ATTN_BACKENDS}")
        if attn_backend != "xla-gather" and page_size is None:
            raise ValueError(
                f"attn_backend {attn_backend!r} requires page_size (the "
                f"paged kernel walks block tables; the dense cache has "
                f"none)")
        if speculate and page_size is None:
            raise ValueError(
                "speculate=True requires page_size (speculative rollback "
                "rides the paged cache's host-mirrored per-row index)")
        if speculate and spec_gamma < 1:
            raise ValueError(f"spec_gamma must be >= 1, got {spec_gamma}")
        if tier is not None and page_size is None:
            raise ValueError(
                "tier requires page_size (the host tier stores paged "
                "KV chains; the dense cache has no page chains to swap)")
        if tier is not None and prompt_cache <= 0:
            raise ValueError(
                "tier requires prompt_cache > 0 (tier entries restore "
                "through the prompt cache's pin/refcount discipline)")
        if tier_watermark < 0:
            raise ValueError(f"tier_watermark must be >= 0, got "
                             f"{tier_watermark}")
        self.qos = bool(qos)
        # Wall clock behind every policy-visible time read (request
        # deadlines, queue expiry — scheduler.py). Injectable so the
        # fleet simulator can drive admission policy at virtual time;
        # watchdog heartbeats stay on time.monotonic (liveness, not
        # policy).
        self._clock = clock
        self.interactive_ttft_slo_s = (
            None if interactive_ttft_slo_s is None
            else float(interactive_ttft_slo_s))
        self.batch_ttft_slo_s = (
            None if batch_ttft_slo_s is None else float(batch_ttft_slo_s))
        self.model = model
        self.params = params
        self.slots = slots
        self.chunk_prefill = chunk_prefill
        self.decode_block = decode_block
        cfg = getattr(model.config, "base", model.config)
        self.max_seq = cfg.max_seq_len
        self.vocab = cfg.vocab_size
        # Multi-LoRA serving (models/lora.py MultiLoraDense): per-slot
        # adapter ids travel as a traced (B,) array, so requests on
        # DIFFERENT fine-tunes share the one decode program/batch. None
        # when the model has no adapter stacks — every core is then
        # called exactly as before (no recompile, no behavior change).
        self.n_adapters = getattr(cfg, "multi_lora", None)

        # Paged KV cache state (cfg doc in models/transformer.py; the
        # serving semantics live in this class's docstring above).
        if num_pages is not None and page_size is None:
            raise ValueError("num_pages needs page_size")
        self.paged = page_size is not None
        self.attn_backend = attn_backend
        if self.paged:
            if page_size < 1 or self.max_seq % page_size:
                raise ValueError(f"page_size {page_size} must divide "
                                 f"max_seq_len {self.max_seq}")
            self.page_size = page_size
            self.n_bt = self.max_seq // page_size  # block-table width
            if num_pages is None:
                num_pages = 1 + slots * self.n_bt  # dense parity + sink
            if num_pages < 2:
                raise ValueError(f"num_pages must be >= 2, got "
                                 f"{num_pages}")
            self.num_pages = num_pages
            self.pmodel = paged_model(model, num_pages=num_pages,
                                      page_size=page_size,
                                      attn_backend=attn_backend)
            self._alloc = _PageAllocator(num_pages)
            self._tables = np.zeros((slots, self.n_bt), np.int32)
            # Host mirror of every row's cache index — the injected
            # truth: each paged dispatch stamps it into the cache first,
            # making the device-side index disposable state.
            self._indices = np.zeros((slots,), np.int32)
            self._chains: "list[list[int]]" = [[] for _ in range(slots)]
            self._pinned: "dict[int, int]" = {}  # page -> #pcache pins

        # Host page tier (serve/tiering.py; loop thread only — HTTP
        # threads reach it through _TierCommand marshalling). _sessions
        # maps a session id to its chain's current pcache/tier key.
        self._tier = tier
        self.tier_watermark = tier_watermark
        self._sessions: "dict[str, tuple]" = {}

        # Speculative decoding state (loop thread only). _spec_hist[r]
        # is row r's prompt + every emitted token — the drafter's
        # lookup corpus; _spec_depth[r] is the row's adaptive proposal
        # budget in [1, spec_gamma].
        self.speculate = speculate
        self.spec_gamma = spec_gamma
        if speculate:
            from k3stpu.serve.speculative import NgramDrafter

            self._drafter = NgramDrafter()
            self._spec_hist: "list[list[int]]" = [[] for _ in range(slots)]
            self._spec_depth = np.full((slots,), spec_gamma, np.int32)

        # Decode-MFU model: one decoded token streams every weight
        # through the MXU once, ~2 flops per param (the standard
        # inference-MFU convention; attention's O(len·d) term is noise
        # next to the weight matmuls at serving batch sizes). Peak is
        # None off-TPU (CPU stand-in) — the MFU gauge then stays 0
        # rather than reporting a meaningless CPU ratio.
        from k3stpu.ops.matmul import peak_tflops_for

        self._decode_flops_per_tok = 2.0 * sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        peak = peak_tflops_for()
        self._peak_flops = None if peak is None else peak * 1e12

        self._cache = init_cache(self.pmodel if self.paged else model,
                                 slots)
        if self.paged:
            # Per-page HBM (all layers: K/V pools + int8 scale planes)
            # — the unit of the pcache byte accounting. Layout-aware:
            # pool leaves are identified BY NAME (`*_pages`, the same
            # rule every paged scatter uses), not by rank — an ndim
            # heuristic silently dropped the int8 pools' (P, ps, H)
            # fp32 scale planes from the count. Matches
            # models/quant.kv_page_bytes leaf for leaf (asserted in
            # tests/test_tiering.py).
            self._page_bytes = sum(
                v.nbytes // num_pages
                for p, v in
                jax.tree_util.tree_flatten_with_path(self._cache)[0]
                if str(getattr(p[-1], "key", "")).endswith("_pages"))
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def _cache_sharding(x):
                # (B, S, H, D) K/V and (B, S, H) scale leaves shard on
                # the head axis; (B,) index and anything indivisible
                # replicate.
                if x.ndim >= 3 and x.shape[2] % mesh.shape["model"] == 0:
                    return NamedSharding(mesh, P(None, None, "model"))
                return NamedSharding(mesh, P())

            self._cache = jax.tree.map(
                lambda x: jax.device_put(x, _cache_sharding(x)),
                self._cache)
        # Serving-side tensor parallelism degree: the mesh's 'model'
        # extent whether the mesh was built here (tp_shards > 1) or
        # handed in pre-built. 1 = monolithic, stats/exposition gated.
        self.tp_shards = int(mesh.shape["model"]) if mesh is not None else 1
        if self.paged:
            # Per-SHARD page bytes: leaves sharded on the head axis put
            # 1/tp of their bytes on each chip; indivisible leaves are
            # replicated and cost full freight everywhere. Matches
            # models/quant.kv_page_bytes(..., tp_shards=) leaf for leaf.
            tp = self.tp_shards
            self._page_bytes_per_shard = sum(
                (v.nbytes // num_pages)
                // (tp if v.ndim >= 3 and v.shape[2] % tp == 0 else 1)
                for p, v in
                jax.tree_util.tree_flatten_with_path(self._cache)[0]
                if str(getattr(p[-1], "key", "")).endswith("_pages"))
        self._base_key = jax.random.key(seed)
        self._step_counter = 0

        # Host-side slot state (numpy: mutated only by the loop thread).
        self._active = np.zeros((slots,), bool)
        self._reserved = np.zeros((slots,), bool)  # chunked admission holds
        self._last_tok = np.zeros((slots,), np.int32)
        self._left = np.zeros((slots,), np.int64)
        self._temps = np.zeros((slots,), np.float32)
        self._topks = np.full((slots,), 1, np.int32)
        self._topps = np.ones((slots,), np.float32)
        self._eos = np.full((slots,), -1, np.int32)
        self._aids = np.zeros((slots,), np.int32)  # multi-LoRA slots
        self._owner: "list[_Request | None]" = [None] * slots
        self._collected: "list[list[int]]" = [[] for _ in range(slots)]

        # Admission bound: requests in flight (queued, admitting, or
        # decoding — counted from enqueue until the consumer returns).
        self.max_pending = max_pending
        self._inflight = 0  # guarded by _lock
        self._q: "queue.SimpleQueue[_Request | None]" = queue.SimpleQueue()
        self._pending: "list[_Request]" = []
        self._adm: "dict | None" = None  # in-flight chunked admission
        self._closed = False
        self._lock = threading.Lock()
        self._obs = obs
        if obs is not None and tp_shards > 1:
            # Stamp the shard-count gauge and sample the cross-shard
            # all-reduce latency once at init (the per-layer psum is
            # fused inside the jitted programs, so a standalone probe
            # is the one place its cost is separable). Gated on the
            # EXPLICIT tp_shards knob — a pre-built mesh alone (the
            # server's multi-device auto-shard) keeps the monolithic
            # exposition byte-stable.
            if getattr(obs, "set_tp_shards", None) is not None:
                obs.set_tp_shards(self.tp_shards)
            self._tp_allreduce_probe()
        if obs is not None and self.qos \
                and getattr(obs, "set_qos", None) is not None:
            # Arm the per-class families only on an EXPLICIT qos engine
            # — a classless deployment's /metrics stays byte-stable.
            obs.set_qos(QOS_CLASSES)
        self._stats = {"tokens": 0, "steps": 0, "dispatches": 0,
                       "busy_s": 0.0, "requests": 0,
                       "slot_occupancy_sum": 0.0, "peak_active_slots": 0,
                       "adm_chunks": 0,
                       "pcache_hits": 0, "pcache_prefix_hits": 0,
                       "pcache_misses": 0, "pcache_bytes": 0,
                       "rejected": 0,
                       # Speculative decoding (docs/SPECULATIVE.md):
                       # proposed/accepted drafts, emitted tokens and
                       # dispatches on the verify path, and iterations
                       # where a verify failure fell back to plain
                       # decode.
                       "spec_dispatches": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "spec_emitted": 0,
                       "spec_fallbacks": 0,
                       # Host page tier (docs/TIERING.md): admission
                       # probes that found / missed a tier chain,
                       # completed swap directions, and swaps that
                       # degraded to a cold prefill.
                       "tier_hits": 0, "tier_misses": 0,
                       "tier_swap_ins": 0, "tier_swap_outs": 0,
                       "tier_fallbacks": 0,
                       # Disagg KV transfer (docs/DISAGG.md): completed
                       # exports/imports, wire bytes moved in either
                       # direction, and handoffs that degraded to a
                       # cold prefill on the decode replica.
                       "kv_exports": 0, "kv_imports": 0,
                       "kv_transfer_bytes": 0, "transfer_fallbacks": 0,
                       # Containment counters (docs/RESILIENCE.md).
                       "deadline_expired": 0, "watchdog_trips": 0,
                       "loop_crashes": 0, "loop_restarts": 0,
                       "breaker_rejected": 0,
                       # QoS (docs/QOS.md): loss-free preemptions,
                       # parks that failed (victim kept running),
                       # predictive-gate rejections, and forecasts
                       # that failed open to FIFO.
                       "preemptions": 0, "preempt_fallbacks": 0,
                       "admission_rejected": 0, "predict_fallbacks": 0}
        # Prompt cache: tuple(prompt tokens) -> (cache_1row, last_1row),
        # insertion-ordered dict as LRU (loop thread only).
        self.prompt_cache = prompt_cache
        self._pcache: "dict[tuple, tuple]" = {}

        # Containment state (docs/RESILIENCE.md). _waiters is every
        # client thread currently blocked on a request's event — the set
        # the watchdog fails with retryable errors when the loop stalls.
        self.breaker = breaker
        self._chaos = chaos
        self.watchdog_s = watchdog_s
        self._waiters: "set[_Request]" = set()  # guarded by _lock
        self._heartbeat = time.monotonic()  # stamped each loop iteration
        self._loop_exc: "BaseException | None" = None

        self._thread = threading.Thread(target=self._loop_main, daemon=True,
                                        name="generate-engine")
        self._thread.start()
        self._watchdog: "threading.Thread | None" = None
        self._wd_stop = threading.Event()
        if watchdog_s is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="engine-watchdog")
            self._watchdog.start()

    # --- lifecycle and stats --------------------------------------------

    def _tp_allreduce_probe(self) -> None:
        """Sample the mesh's cross-shard all-reduce latency.

        One tiny jitted sum over a 'model'-sharded array IS an
        all-reduce on the wire; three timed repetitions after a warmup
        feed ``k3stpu_serve_tp_allreduce_seconds`` so the histogram
        carries the collective's standalone cost (inside the decode
        programs it is fused and overlapped — unobservable on its own).
        """
        obs = self._obs
        if obs is None or getattr(obs, "on_tp_allreduce", None) is None:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(
            np.ones((self.tp_shards, 256), np.float32),
            NamedSharding(self.mesh, P("model", None)))
        f = jax.jit(lambda a: jnp.sum(a, axis=0))
        jax.block_until_ready(f(x))  # compile outside the timed region
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(f(x))
            obs.on_tp_allreduce(time.perf_counter() - t0)

    def close(self) -> None:
        self._closed = True
        self._wd_stop.set()
        self._q.put(None)
        self._thread.join(timeout=60)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)

    def loop_alive(self) -> bool:
        """Liveness of the engine loop thread (the server's /healthz
        consults this; the watchdog revives a dead loop, so not-alive is
        a transient not-ready, not a terminal state)."""
        return self._thread.is_alive()

    def reset_stats(self) -> None:
        """Zero the counters (post-warmup: compile-dominated dispatches
        would poison the reported tokens_per_s). pcache_bytes is live
        state, not a counter — it survives the reset."""
        with self._lock:
            keep = self._stats["pcache_bytes"]
            for k in self._stats:
                self._stats[k] = type(self._stats[k])()
            self._stats["pcache_bytes"] = keep
        if self._obs is not None:
            self._obs.reset()

    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
        s["tokens_per_s"] = (round(s["tokens"] / s["busy_s"], 2)
                             if s["busy_s"] > 0 else None)
        s["avg_active_slots"] = (round(s["slot_occupancy_sum"] / s["steps"],
                                       2) if s["steps"] else None)
        s["pcache_entries"] = len(self._pcache)
        s["attn_backend"] = self.attn_backend
        s["tp_shards"] = self.tp_shards
        if self.breaker is not None:
            s["breaker_state"] = self.breaker.state()
            s["breaker_trips"] = self.breaker.trips
        if self.paged:
            total, free = self._alloc.total, self._alloc.free
            s["pages_total"] = total
            s["pages_free"] = free
            s["pages_resident"] = total - free
            s["pages_pinned"] = len(self._pinned)
            if self._tier is not None:
                ts = self._tier.stats()
                s["host_tier_pages"] = ts.pop("tier_pages")
                s.update(ts)
                s["sessions_tracked"] = len(self._sessions)
            s["page_utilization"] = round((total - free) / total, 4)
            # HBM planning surface (docs/ARCHITECTURE.md sizing recipe):
            # per-page bytes for the whole pool and for ONE shard's
            # slice of it — at tp_shards=1 they coincide.
            s["page_bytes"] = self._page_bytes
            s["page_bytes_per_shard"] = self._page_bytes_per_shard
            # Pinned pages with >1 reference ARE the zero-copy sharing:
            # mapped read-only into a live row's table, or claimed by
            # several cache entries (an extended prompt shares its
            # ancestor's full pages).
            s["pcache_shared_pages"] = sum(
                1 for p in list(self._pinned)
                if self._alloc.refcount(p) > 1)
            # Token-slots a dense cache needs for this many slots vs
            # what the pool actually holds — the measured density
            # multiplier (> 1: same slot count in less HBM).
            s["paged_density_ratio"] = round(
                self.slots * self.max_seq / (total * self.page_size), 2)
        if self.speculate:
            s["spec_accept_rate"] = (
                round(s["spec_accepted"] / s["spec_proposed"], 4)
                if s["spec_proposed"] else None)
            s["spec_tokens_per_dispatch"] = (
                round(s["spec_emitted"] / s["spec_dispatches"], 2)
                if s["spec_dispatches"] else None)
        return s

    # --- crash containment (docs/RESILIENCE.md) -------------------------

    def _crash_reset(self, err: Exception) -> None:
        """Crash-only containment after an unexpected dispatch failure
        (or a dead loop thread being revived): fail everything holding
        device state CLEANLY, then rebuild the host-side cache
        bookkeeping to a verified-empty baseline. The KV pool arrays
        themselves need no scrubbing — rows/pages are fully overwritten
        at admission, and junk beyond a row's index is invisible to the
        position mask — but the prompt cache and page chains may
        reference state the failed dispatch left unknown, so both are
        dropped wholesale. Queued/pending requests survive: they hold no
        device state and the resumed loop serves them."""
        for req in {o for o in self._owner if o is not None}:
            req.error = err
            req.signal()
        if self._adm is not None:
            a, self._adm = self._adm, None
            a["req"].error = err
            a["req"].signal()
        self._active[:] = False
        self._reserved[:] = False
        self._owner = [None] * self.slots
        self._collected = [[] for _ in range(self.slots)]
        self._temps[:] = 0.0  # keep the all-greedy fast path alive
        if self.speculate:
            self._spec_hist = [[] for _ in range(self.slots)]
            self._spec_depth[:] = self.spec_gamma
        # The pcache drops WHOLESALE, no tier swap-out: the failed
        # dispatch left device state untrusted, and gathering unknown
        # bytes to host would let corruption outlive the reset. Chains
        # already on the host tier are fine (they reference no device
        # pages) — sessions keep only the keys the tier still holds.
        self._pcache.clear()
        self._sessions = (
            {sid: k for sid, k in self._sessions.items()
             if self._tier is not None and self._tier.contains(k)})
        with self._lock:
            self._stats["pcache_bytes"] = 0
            self._stats["loop_crashes"] += 1
        if self.paged:
            self._alloc = _PageAllocator(self.num_pages)
            self._pinned = {}
            self._chains = [[] for _ in range(self.slots)]
            self._tables[:] = 0
            self._indices[:] = 0
            if self._alloc.free != self._alloc.total:  # verified-empty
                raise RuntimeError(
                    f"allocator reset left {self._alloc.total - self._alloc.free} "
                    f"pages unaccounted")

    def _watchdog_loop(self) -> None:
        """Detects (a) a dead loop thread — revives it after a crash
        reset — and (b) a stalled loop (a wedged device dispatch: the
        heartbeat, stamped once per iteration, goes stale; a HEALTHY
        idle loop wakes every 0.2 s via _drain_queue's timeout). A stall
        fails every blocked client with a retryable EngineStalled
        instead of letting them hang to their full timeout, and trips
        the breaker so /healthz pulls the pod from rotation."""
        poll = max(0.01, min(self.watchdog_s / 4.0, 1.0))
        while not self._wd_stop.wait(poll):
            if self._closed:
                return
            if not self._thread.is_alive():
                self._revive_loop()
                continue
            if time.monotonic() - self._heartbeat < self.watchdog_s:
                continue
            with self._lock:
                waiters = list(self._waiters)
            if not waiters:
                continue  # nobody is blocked on the stalled loop
            with self._lock:
                self._stats["watchdog_trips"] += 1
            if self.breaker is not None:
                self.breaker.trip_open()
            err = EngineStalled(
                f"engine loop made no dispatch progress for "
                f">= {self.watchdog_s:.1f}s; request failed cleanly, retry")
            for req in waiters:
                # deadline 0 makes the loop reap the rows/queue entry via
                # _expire_deadlines whenever it resumes; the waiter is
                # released NOW.
                req.deadline = 0.0
                req.error = err
                req.signal()
            # A trip consumes the stale window: the next trip requires
            # another full watchdog_s of no progress. Without this, a
            # request arriving while the loop is still wedged is failed on
            # the very next poll tick instead of getting its own grace
            # period to see the loop recover.
            self._heartbeat = time.monotonic()

    def _revive_loop(self) -> None:
        """The loop thread died (an exception escaped _loop — e.g. an
        injected engine_loop fault). Crash-reset its state and start a
        fresh thread; this runs on the watchdog thread, which is safe
        only BECAUSE the loop thread is dead."""
        if self._closed:
            return
        exc, self._loop_exc = self._loop_exc, None
        err = EngineStalled(
            f"engine loop thread died ({exc!r}); state reset, retry")
        self._record_backend_failure()
        self._crash_reset(err)
        with self._lock:
            self._stats["loop_restarts"] += 1
        self._thread = threading.Thread(target=self._loop_main, daemon=True,
                                        name="generate-engine")
        self._thread.start()

    # --- the decode loop (single thread; owns all slot state) -----------

    def _spec_iteration(self, aids, t0: float) -> bool:
        """One speculative decode iteration: draft per-row proposals,
        verify them in ONE batch-wide extend, emit each row's accepted
        prefix + the target's correction token. Returns True when it
        handled the dispatch (all bookkeeping done, loop continues);
        False falls through to the plain decode path — taken when no
        row proposes anything, any row samples (verify is argmax-only),
        any row sits too close to the cache end for the static verify
        width, or the verify dispatch itself fails (chaos ``spec_verify``
        or a real backend error: that batch decodes plainly instead of
        wedging the loop).

        Exactness: the verify extend over ``[x0, d1..d_gamma]`` is
        computationally identical to the plain path decoding x0, d1,
        ... in sequence — accepted positions get exactly the K/V the
        plain path would have written, and the host index advances by
        exactly the tokens consumed (m accepted drafts + x0), so the
        correction token's K/V lands on the NEXT dispatch as that
        chunk's position 0, same as plain decode. Rejected-draft writes
        sit past the new index: invisible to the position mask and
        overwritten before the index ever reaches them."""
        W = self.spec_gamma + 1
        if (self._temps > 0.0).any():
            return False
        # Static verify width vs cache end: a chunk always writes W
        # positions, and a row within W of max_seq would clamp those
        # writes back INTO its own last page (the plain path's harmless
        # finished-row clamp is harmful here: extend's attention reads
        # the corruption in the same call). Rare and transient — such
        # rows are at most spec_gamma tokens from finishing.
        if bool((self._indices[self._active] + W > self.max_seq).any()):
            return False
        t_draft = time.perf_counter()
        props: "list[list[int]]" = [[] for _ in range(self.slots)]
        any_prop = False
        for r in range(self.slots):
            if not self._active[r]:
                continue
            depth = int(min(self._spec_depth[r], self._left[r] - 1))
            if depth <= 0:
                continue
            p = self._drafter.propose(self._spec_hist[r], depth)
            if p:
                props[r] = p
                any_prop = True
        if not any_prop:
            return False
        draft_s = time.perf_counter() - t_draft
        chunk = np.zeros((self.slots, W), np.int32)
        chunk[:, 0] = self._last_tok
        for r in range(self.slots):
            if props[r]:
                chunk[r, 1:1 + len(props[r])] = props[r]
        t_verify = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("spec_verify")
            self._cache, tgt = self._spec_verify(
                self.params, self._cache, jnp.asarray(self._indices),
                jnp.asarray(self._tables), jnp.asarray(chunk), aids)
            tgt = np.asarray(tgt)
        except Exception:  # noqa: BLE001 — plain decode serves this batch
            with self._lock:
                self._stats["spec_fallbacks"] += 1
            return False
        verify_s = time.perf_counter() - t_verify
        if self.breaker is not None:
            self.breaker.record_success()
        dt = time.perf_counter() - t0
        n_active = int(self._active.sum())
        done_reqs = set()
        deltas: "dict[_Request, dict[int, list[int]]]" = {}
        consumed = proposed = accepted = 0
        for r in range(self.slots):
            if not self._active[r]:
                continue
            plen = len(props[r])
            m = 0
            while m < plen and props[r][m] == int(tgt[r, m]):
                m += 1
            proposed += plen
            accepted += m
            if plen:
                # Per-slot depth adaptation: full accept earns a deeper
                # next proposal, full reject a shallower one. Depth only
                # changes how much is PROPOSED — never what is emitted —
                # so exactness is adaptation-blind.
                if m == plen:
                    self._spec_depth[r] = min(self._spec_depth[r] + 1,
                                              self.spec_gamma)
                elif m == 0:
                    self._spec_depth[r] = max(1, self._spec_depth[r] - 1)
            emitted = props[r][:m] + [int(tgt[r, m])]
            owner = self._owner[r]
            row_consumed = 0
            for tok in emitted:
                self._last_tok[r] = tok
                self._collected[r].append(tok)
                self._spec_hist[r].append(tok)
                self._left[r] -= 1
                row_consumed += 1
                if owner is not None and owner.stream_q is not None:
                    deltas.setdefault(owner, {}).setdefault(
                        owner.slot_rows.index(r), []).append(tok)
                if self._left[r] <= 0 or (self._eos[r] >= 0
                                          and tok == self._eos[r]):
                    self._finish_row(r)
                    done_reqs.add(owner)
                    break  # tokens past eos/budget are discarded
            consumed += row_consumed
            # Cache truth after this dispatch: positions index+1 ..
            # index+row_consumed hold x0 + the accepted drafts' K/V
            # (an eos-truncated row advances less, but it just finished
            # — its next use rewrites index and table wholesale).
            self._indices[r] += row_consumed
        for req, d in deltas.items():
            req.stream_q.put(d)
        with self._lock:
            # One extend over the batch ~= one device decode step of
            # work, so "steps" (the per-step unit avg_active_slots
            # divides by) advances by 1 while "tokens" advances by
            # everything emitted — tokens/dispatches IS the speculation
            # win, spec_accepted/spec_proposed the acceptance rate.
            self._stats["steps"] += 1
            self._stats["dispatches"] += 1
            self._stats["tokens"] += consumed
            self._stats["busy_s"] += dt
            self._stats["slot_occupancy_sum"] += n_active
            self._stats["peak_active_slots"] = max(
                self._stats["peak_active_slots"], n_active)
            self._stats["spec_dispatches"] += 1
            self._stats["spec_proposed"] += proposed
            self._stats["spec_accepted"] += accepted
            self._stats["spec_emitted"] += consumed
        if self._obs is not None:
            self._obs.on_dispatch(n_active, len(self._pending),
                                  self._alloc.free,
                                  self._alloc.total - self._alloc.free)
            self._obs.on_decode_dispatch(dt, self._decode_mfu(consumed, dt))
            self._obs.on_spec_dispatch(proposed, accepted, consumed,
                                       draft_s, verify_s)
            if self._obs.enabled:
                seen = set()
                attrs = {"spec": True, "proposed": proposed,
                         "accepted": accepted, "active": n_active,
                         "dt_ms": round(dt * 1e3, 3)}
                for r in range(self.slots):
                    o = self._owner[r]
                    if o is None or o.trace is None or id(o) in seen:
                        continue
                    seen.add(id(o))
                    o.trace.event("decode", attrs)
        for req in done_reqs:
            self._maybe_complete(req)
        return True

    def _loop_main(self) -> None:
        try:
            self._loop()
        except Exception as e:  # noqa: BLE001 — crash-only: watchdog revives
            self._loop_exc = e

    def _loop(self) -> None:
        while True:
            self._heartbeat = time.monotonic()
            if self._chaos is not None:
                # Outside the dispatch try on purpose: a raised fault
                # here kills the loop thread (the watchdog-revival path).
                self._chaos.fire("engine_loop")
            any_active = bool(self._active.any())
            if not self._drain_queue(block=not any_active
                                     and not self._pending
                                     and self._adm is None):
                break  # shutdown sentinel
            self._expire_deadlines()
            self._admit()
            if self.qos and self._obs is not None:
                n_batch = sum(1 for r in self._pending
                              if r.priority == "batch")
                self._obs.on_class_queue_depth(
                    "interactive", len(self._pending) - n_batch)
                self._obs.on_class_queue_depth("batch", n_batch)
            if (self.paged and self._tier is not None
                    and self.tier_watermark > 0):
                self._tier_pressure()
            if not self._active.any():
                continue
            t0 = time.perf_counter()
            self._step_counter += 1
            k_tok = self.decode_block
            aids = (jnp.asarray(self._aids)
                    if self.n_adapters is not None else None)
            if self.speculate and self._spec_iteration(aids, t0):
                continue
            try:
                if self._chaos is not None:
                    self._chaos.fire("decode_dispatch")
                targs = (jnp.asarray(self._last_tok),
                         jnp.asarray(self._temps),
                         jnp.asarray(self._topks),
                         jnp.asarray(self._topps),
                         self._step_counter, self._base_key)
                if self.paged:
                    pargs = (jnp.asarray(self._indices),
                             jnp.asarray(self._tables))
                    if k_tok == 1:
                        self._cache, nxt = self._paged_decode_step(
                            self.params, self._cache, *pargs, *targs,
                            aids)
                        block = np.asarray(nxt)[None]      # (1, B)
                    else:
                        self._cache, nxt = self._paged_decode_block_step(
                            self.params, self._cache, *pargs, *targs,
                            k_tok, aids)
                        block = np.asarray(nxt)            # (K, B)
                    # The dispatch advanced EVERY row's device index by
                    # k_tok; the host mirror (the injected truth) must
                    # track it, active or not — exactly like the dense
                    # cache's own index leaves.
                    self._indices += k_tok
                elif k_tok == 1:
                    self._cache, nxt = self._decode_step(
                        self.params, self._cache, *targs, aids)
                    block = np.asarray(nxt)[None]          # (1, B)
                else:
                    self._cache, nxt = self._decode_block_step(
                        self.params, self._cache, *targs, k_tok, aids)
                    block = np.asarray(nxt)                # (K, B)
            except Exception as e:  # noqa: BLE001 — crash-only reset
                self._record_backend_failure()
                self._crash_reset(e)
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            dt = time.perf_counter() - t0
            n_active = int(self._active.sum())
            done_reqs = set()
            consumed = 0
            deltas: "dict[_Request, dict[int, list[int]]]" = {}
            for j in range(block.shape[0]):
                for r in range(self.slots):
                    if not self._active[r]:
                        continue  # finished mid-block: surplus discarded
                    tok = int(block[j, r])
                    self._last_tok[r] = tok
                    self._collected[r].append(tok)
                    if self.speculate:
                        self._spec_hist[r].append(tok)
                    self._left[r] -= 1
                    consumed += 1
                    owner = self._owner[r]
                    if owner is not None and owner.stream_q is not None:
                        deltas.setdefault(owner, {}).setdefault(
                            owner.slot_rows.index(r), []).append(tok)
                    if self._left[r] <= 0 or (self._eos[r] >= 0
                                              and tok == self._eos[r]):
                        self._finish_row(r)
                        done_reqs.add(owner)
            # Deltas flush BEFORE completion: the terminal marker from
            # signal() must be the stream's last item.
            for req, d in deltas.items():
                req.stream_q.put(d)
            with self._lock:
                # "steps" keeps its per-token meaning (device decode
                # steps) so the exported counter's unit survives the
                # k>1 default; "dispatches" counts device round-trips —
                # steps/dispatches is the realized block amortization.
                self._stats["steps"] += block.shape[0]
                self._stats["dispatches"] += 1
                self._stats["tokens"] += consumed
                self._stats["busy_s"] += dt
                self._stats["slot_occupancy_sum"] += (n_active
                                                      * block.shape[0])
                self._stats["peak_active_slots"] = max(
                    self._stats["peak_active_slots"], n_active)
            if self._obs is not None:
                self._obs.on_dispatch(
                    n_active, len(self._pending),
                    self._alloc.free if self.paged else None,
                    (self._alloc.total - self._alloc.free)
                    if self.paged else None)
                self._obs.on_decode_dispatch(
                    dt, self._decode_mfu(consumed, dt))
                if self._obs.enabled:
                    # One "decode" event per request per dispatch (not
                    # per token): slots is small, so this scan is noise
                    # next to the device round-trip above.
                    seen = set()
                    attrs = {"k": block.shape[0], "active": n_active,
                             "dt_ms": round(dt * 1e3, 3)}
                    for r in range(self.slots):
                        o = self._owner[r]
                        if (o is None or o.trace is None
                                or id(o) in seen):
                            continue
                        seen.add(id(o))
                        o.trace.event("decode", attrs)
            for req in done_reqs:
                self._maybe_complete(req)
        # Shutdown: fail anything still waiting — INCLUDING requests a
        # racing submit() enqueued behind the sentinel (they would
        # otherwise block their caller for the full submit timeout).
        err = RuntimeError("engine closed")
        try:
            while True:
                req = self._q.get(block=False)
                if req is not None:
                    self._pending.append(req)
        except queue.Empty:
            pass
        if self._adm is not None:
            self._pending.append(self._adm["req"])
            self._adm = None
        for req in self._pending:
            req.error = err
            req.signal()
        for req in {o for o in self._owner if o is not None}:
            req.error = err
            req.signal()
