"""Continuous batching for LM generation — slot-based decode scheduling.

``generate_tokens`` runs whole requests back-to-back: a 256-token
generation holds the chip while later requests queue, and a batch-1
request decodes alone at batch-1 arithmetic intensity. This engine is the
TPU-native fix (the serving pattern vLLM/Orca made standard, built here on
XLA-static shapes):

- ONE decode program, compiled once, over a fixed block of ``slots`` cache
  rows. Every step advances all active slots together; per-row cache
  indices (models/transformer.py) let rows sit at different depths.
- Requests JOIN mid-flight: a free slot gets the new request's prefilled
  cache rows scattered in between decode steps; finished slots free
  immediately. No request waits for another to finish, and decode batch
  density — the thing MXU throughput scales with — stays high under load.
- Everything device-side is shape-static: prefill widths and admitted-row
  counts come from small power-of-two bucket sets, so steady state runs a
  handful of compiled programs, never a recompile.
- Per-slot sampling params travel as traced (B,) arrays (temperature,
  top-k, eos), so heterogeneous requests share the one decode program.

The reference has no serving scheduler at all (its workload is a stock
binary behind a Service, reference jellyfin.yaml:1-43); this is the
match-or-beat half of the serving story.
"""

from __future__ import annotations

import functools
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import init_cache, paged_model, set_cache_index
from k3stpu.serve.containment import CircuitOpen, EngineStalled
from k3stpu.serve.programs import (
    decode_core,
    extend_core,
    prefill_core,
    prompt_width_bucket,
)

_NEG_INF = -1e30


class EngineOverloaded(RuntimeError):
    """Raised by submit paths when max_pending requests are already in
    flight — the backpressure signal the HTTP layer turns into a 503
    (shed load at the door; queueing unboundedly just converts overload
    into client timeouts plus held memory)."""


def _pow2_at_least(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


class _PageAllocator:
    """Host-side page bookkeeping for the paged KV cache (loop thread
    only). Page 0 is the reserved sink — pad rows and neutralized batch
    rows write there — so it is never handed out. Sharing (prompt-cache
    pins, sampled fan-outs) is refcounted: a page returns to the free
    list only when its last reference drops."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._rc = np.zeros((num_pages,), np.int32)
        self._free = list(range(num_pages - 1, 0, -1))  # pop() hands out 1 first

    @property
    def total(self) -> int:
        return self.num_pages - 1  # the sink page is not allocatable

    @property
    def free(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._rc[page])

    def alloc(self, n: int) -> "list[int] | None":
        """n fresh pages at refcount 1, or None (all-or-nothing)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._rc[pages] = 1
        return pages

    def incref(self, pages) -> None:
        for p in pages:
            if self._rc[p] <= 0:
                raise RuntimeError(f"incref on free page {p}")
            self._rc[p] += 1

    def decref(self, pages) -> None:
        for p in pages:
            if self._rc[p] <= 0:
                raise RuntimeError(f"double free of page {p}")
            self._rc[p] -= 1
            if self._rc[p] == 0:
                self._free.append(p)


def _sample_rows(logits, temps, topks, topps, key):
    """Per-row sampling over (B, V) logits: temperature <= 0 is greedy;
    top-k cuts below each row's own k-th value (k == V disables); top-p
    keeps each row's smallest nucleus reaching mass p (1.0 disables).

    The all-greedy batch — the dominant serving case, and every decode
    step of the exactness-pinned capture runs — skips the sampling
    machinery entirely via ``lax.cond``: the mixed path pays two full
    (B, V) sorts (top-k kth-value + top-p nucleus) per step, pure
    VPU/HBM waste when no row will use the result."""
    from k3stpu.models.generate import top_p_mask

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def mixed(_):
        v = logits.shape[-1]
        scaled = logits / jnp.clip(temps, 1e-6, None)[:, None]
        srt = jnp.sort(scaled, axis=-1)
        kth = jnp.take_along_axis(
            srt, (v - jnp.clip(topks, 1, v))[:, None], axis=-1)
        cut = jnp.where(scaled < kth, _NEG_INF, scaled)
        cut = top_p_mask(cut, topps)
        sampled = jax.random.categorical(key, cut,
                                         axis=-1).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    return jax.lax.cond(jnp.all(temps <= 0.0), lambda _: greedy, mixed,
                        None)


class _Request:
    __slots__ = ("block", "lens", "budget", "temp", "top_k", "top_p",
                 "eos", "event", "tokens", "error", "slot_rows", "samples",
                 "deadline", "stream_q", "_ptuple", "probe", "adapter",
                 "trace", "trace_id", "session")

    def __init__(self, block, lens, budget, temp, top_k, eos, samples=1,
                 top_p=None, adapter=0):
        self.block = block          # (n, P) int32, right-padded
        self.lens = lens            # (n,) true lengths
        self.budget = budget        # max new tokens (shared by the rows)
        self.temp = temp
        self.top_k = top_k
        self.top_p = top_p          # float | None (None == 1.0, no cut)
        self.eos = eos              # int | None
        self.samples = samples      # >1: one prompt, n sampled rows
        self.adapter = adapter      # multi-LoRA slot (0 = base)
        self.event = threading.Event()
        self.tokens: "list[list[int]] | None" = None
        self.error: "Exception | None" = None
        self.slot_rows: "list[int]" = []
        self.deadline: float = float("inf")  # set by _enqueue_and_wait
        # submit_stream() installs a queue here; the loop thread pushes
        # per-block token deltas into it and signal() pushes the terminal
        # None. Non-streaming requests leave it None (zero overhead).
        self.stream_q: "queue.SimpleQueue | None" = None
        self._ptuple: "tuple | None" = None  # memoized prompt key
        # Lifecycle trace (k3stpu.obs.ReqTrace), set at enqueue when the
        # engine carries a ServeObs; None costs nothing on any path.
        self.trace = None
        # W3C trace id (32 validated lowercase-hex chars) assigned at
        # the HTTP edge; None for direct submits. Only parse_traceparent
        # output ever lands here — raw header bytes never reach the
        # engine.
        self.trace_id: "str | None" = None
        # Memoized prompt-cache probe result (pkey, pentry) — the probe
        # re-runs every loop iteration while the request waits for free
        # slots, and re-scanning the cache each time is pure engine-
        # thread waste. A stale entry stays CORRECT (immutable arrays);
        # the only cost is missing a better prefix inserted meanwhile.
        self.probe: "tuple | None" = None
        # Session id (paged mode): names this request's finished KV
        # chain in the prompt cache / host tier so the session's next
        # turn restores it instead of re-prefilling. None = one-shot.
        self.session: "str | None" = None

    def ptuple(self) -> tuple:
        """The single-prompt cache key, computed once — the admission
        probe re-runs while a request waits for free slots, and an
        O(prompt) conversion per loop iteration on the engine thread
        is waste (the block is immutable after packing)."""
        if self._ptuple is None:
            self._ptuple = tuple(
                int(t) for t in self.block[0, :int(self.lens[0])])
        return self._ptuple

    def signal(self) -> None:
        """Wake the submitter on EVERY terminal path (tokens ready, error,
        expiry, shutdown): terminal stream marker first, THEN the event —
        a streaming consumer must never wait on a queue nobody will feed
        again. Being the single terminal funnel, this is also where the
        lifecycle trace retires (finish() is idempotent — the success
        path already closed it with completion timings)."""
        if self.trace is not None:
            if self.error is not None:
                self.trace.finish("error", repr(self.error))
            else:
                self.trace.finish("ok")
        if self.stream_q is not None:
            self.stream_q.put(None)
        self.event.set()


class _TierCommand:
    """A control message riding the request queue: allocator / prompt
    cache / tier state belongs to the loop thread alone, so HTTP-thread
    operations on it (session release) marshal through ``_q`` and run
    inline at drain. Duck-types the slice of ``_Request`` the loop's
    shutdown tail touches (``error`` + ``signal()`` + ``deadline``) so
    a command stranded behind the close sentinel fails cleanly instead
    of hanging its caller."""

    __slots__ = ("kind", "session", "spill", "event", "result", "error",
                 "deadline", "tokens", "stream_q", "trace")

    def __init__(self, kind: str, session: str, spill: bool = False):
        self.kind = kind
        self.session = session
        self.spill = spill
        self.event = threading.Event()
        self.result = None
        self.error: "Exception | None" = None
        self.deadline = float("inf")  # commands never expire
        self.tokens = None
        self.stream_q = None
        self.trace = None

    def signal(self) -> None:
        self.event.set()


class GenerateEngine:
    """Owns a ``slots``-row KV cache and a single decode loop thread.

    ``submit()`` blocks the calling (HTTP handler) thread until its
    request's rows finish; the loop thread interleaves every live request
    into one decode batch. ``close()`` drains and stops the thread.
    """

    def __init__(self, model, params, *, slots: int = 8,
                 seed: int = 0, chunk_prefill: "int | None" = None,
                 decode_block: int = 1, prompt_cache: int = 0,
                 mesh=None, max_pending: "int | None" = None,
                 page_size: "int | None" = None,
                 num_pages: "int | None" = None,
                 attn_backend: str = "xla-gather",
                 speculate: bool = False, spec_gamma: int = 4,
                 obs=None,
                 breaker=None, watchdog_s: "float | None" = None,
                 chaos=None, tier=None, tier_watermark: int = 0):
        """``chunk_prefill``: admit long prompts in chunks of this many
        tokens, one chunk per loop iteration — bounds how long a decode
        step can be delayed by an arriving prompt to one chunk's latency
        instead of the whole prompt's. None = single-shot admission.

        ``decode_block``: decode this many tokens per device dispatch
        (an inner ``lax.scan``), host-side eos/budget/deadline checks in
        between blocks. Through a relayed backend each dispatch costs
        ~8 ms regardless of work, capping a per-token loop at ~125
        steps/s; a K-token block amortizes that floor K-fold. Trade-off:
        a new request joins on a block boundary (K-token granularity),
        and a row that hits eos mid-block rides out the rest of the
        block with its surplus tokens discarded host-side.

        ``prompt_cache``: keep up to this many prefilled single-prompt
        KV rows (LRU) keyed by the exact prompt tokens. A repeat prompt
        skips its prefill entirely; a prompt that EXTENDS a cached one
        restores the row and appends only the new tokens (the chat /
        shared-system-prompt pattern — prefill cost drops from O(whole
        prompt) to O(new suffix)). Cost: one full-depth cache row of
        HBM per entry (``stats()['pcache_bytes']``). Outputs are
        bit-identical to the uncached path: the restored row IS the
        prefilled row (jax arrays are immutable, so a cached row can't
        be corrupted by the decodes of the slot it was scattered into),
        and the suffix-append reuses the chunked-admission finalize
        invariant (junk K/V beyond a row's index is invisible to the
        position mask and gets overwritten slot-by-slot). 0 disables.

        ``mesh``: tensor-parallel serving over a jax Mesh with a
        'model' axis (parallel/mesh.make_mesh's convention — required).
        The params arrive sharded over that axis
        (parallel/sharding.py); the KV cache must live on the SAME
        devices or jit refuses the mixed placement, so it goes up
        sharded on its kv-head axis where divisible (attention splits
        by head under TP) and replicated otherwise. Host-side numpy
        inputs stay uncommitted — jit places them. None =
        single-device (programs unchanged).

        ``page_size`` / ``num_pages``: PAGED KV cache. The decode cache
        becomes one pool of ``num_pages`` fixed pages per layer instead
        of ``slots`` monolithic ``max_seq``-deep rows; each slot holds a
        chain of just ``ceil((len + budget) / page_size)`` pages,
        addressed through a traced block table — so admission is bounded
        by FREE PAGES, not free rows, and the same HBM serves far more
        concurrent short requests (``stats()['paged_density_ratio']``).
        ``num_pages`` defaults to the dense footprint + the sink page;
        set it LOWER to realize the density win. The prompt cache
        upgrades to zero-copy prefix sharing: entries pin their pages
        (refcounted, read-only) into admitted rows' tables instead of
        copying whole cache rows; only a partial tail page is copied
        (the row writes into it). Token streams stay bit-identical to
        the dense engine's. None = dense cache (everything unchanged).

        ``attn_backend``: how the paged decode/extend path reads the KV
        pool (cfg.attn_backend doc in models/transformer.py).
        ``"xla-gather"`` (default) materializes gathered pages in XLA;
        ``"pallas-paged"`` walks block tables inside the fused Pallas
        kernel (ops/paged_attention.py) — token-identical under greedy
        decoding, no gather materialization. Requires paged mode; off
        TPU the kernel runs in interpreter mode (slow — tests only).

        ``speculate`` / ``spec_gamma``: draft-then-verify speculative
        decoding inside the slot loop (paged mode only — the host
        index mirror is what makes per-row rollback free). Each
        iteration an ``NgramDrafter`` (serve/speculative.py) proposes
        up to ``spec_gamma`` continuation tokens per active row from
        the row's own prompt+generated history; one batch-wide verify
        dispatch (a static ``(slots, spec_gamma+1)`` extend — one
        compile, zero steady-state recompiles) scores every proposal,
        and each row emits its matched prefix plus the target's own
        token at the first divergence — up to ``spec_gamma + 1``
        tokens per dispatch instead of ``decode_block`` device steps'
        worth. Greedy verification means output stays token-identical
        to the non-speculative engine and to ``generate()``; rejected
        proposals roll back for free through the host index mirror.
        Per-slot speculation depth adapts to recent acceptance (full
        accept grows it toward ``spec_gamma``, full reject shrinks it
        toward 1) so rows whose continuation stopped repeating stop
        paying draft+verify for doomed proposals. Iterations where no
        row has a proposal — or any row samples (temperature > 0), or
        a row sits within ``spec_gamma`` tokens of ``max_seq_len`` —
        fall through to the plain decode path unchanged, which is why
        non-repetitive traffic keeps plain-path throughput.

        ``obs``: a ``k3stpu.obs.ServeObs`` to record per-request
        lifecycle traces and latency histograms into (the server shares
        one instance so /metrics and /debug/* see engine traffic).
        None = no recording, zero overhead on every path.

        ``breaker``: a ``containment.CircuitBreaker``. Backend dispatch
        failures feed it; while open, admission raises ``CircuitOpen``
        (HTTP 503 + Retry-After, ``/healthz`` not-ready) until a
        half-open probe request succeeds. None = no breaker.

        ``watchdog_s``: start a watchdog thread that fails in-flight
        requests with retryable ``EngineStalled`` errors when the loop
        makes no progress for this many seconds (a wedged backend
        dispatch), and revives the loop thread if it dies. Must exceed
        the worst-case single dispatch (including cold compiles). None =
        no watchdog (the library default; the HTTP server turns it on).

        ``chaos``: a ``k3stpu.chaos.FaultInjector`` consulted at the
        loop/dispatch/allocator fault boundaries. None (the default) =
        no injection, zero overhead — production paths never arm this.

        ``tier`` / ``tier_watermark``: host-memory KV page tier
        (``serve/tiering.HostPageStore`` — paged mode + prompt_cache
        only). Prompt-cache evictions GATHER their page chains to host
        RAM instead of dropping them; the admission probe checks the
        tier before declaring a pcache miss and restores a match into
        fresh pages (one batched device_put + scatter), token-identical
        to a never-swapped run. When ``tier_watermark`` > 0 the loop
        proactively swaps out LRU pcache entries whenever
        ``pages_free`` sits below it, so HBM pressure converts idle
        sessions into host bytes instead of admission stalls. A failed
        swap-in (chaos ``tier_swap``, torn disk spill) degrades to a
        cold prefill — counted in ``tier_fallbacks``, live rows
        untouched. ``release_session(sid)`` force-evicts a session's
        chain to the tier between turns (docs/TIERING.md)."""
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if mesh is not None and "model" not in mesh.shape:
            raise ValueError(
                f"engine mesh needs a 'model' axis, got {mesh.shape}")
        if chunk_prefill is not None and chunk_prefill < 1:
            raise ValueError(f"chunk_prefill must be >= 1, got "
                             f"{chunk_prefill}")
        if decode_block < 1:
            raise ValueError(f"decode_block must be >= 1, got "
                             f"{decode_block}")
        if prompt_cache < 0:
            raise ValueError(f"prompt_cache must be >= 0, got "
                             f"{prompt_cache}")
        if watchdog_s is not None and watchdog_s <= 0:
            raise ValueError(f"watchdog_s must be > 0, got {watchdog_s}")
        from k3stpu.models.transformer import ATTN_BACKENDS
        if attn_backend not in ATTN_BACKENDS:
            raise ValueError(f"attn_backend {attn_backend!r} not in "
                             f"{ATTN_BACKENDS}")
        if attn_backend != "xla-gather" and page_size is None:
            raise ValueError(
                f"attn_backend {attn_backend!r} requires page_size (the "
                f"paged kernel walks block tables; the dense cache has "
                f"none)")
        if speculate and page_size is None:
            raise ValueError(
                "speculate=True requires page_size (speculative rollback "
                "rides the paged cache's host-mirrored per-row index)")
        if speculate and spec_gamma < 1:
            raise ValueError(f"spec_gamma must be >= 1, got {spec_gamma}")
        if tier is not None and page_size is None:
            raise ValueError(
                "tier requires page_size (the host tier stores paged "
                "KV chains; the dense cache has no page chains to swap)")
        if tier is not None and prompt_cache <= 0:
            raise ValueError(
                "tier requires prompt_cache > 0 (tier entries restore "
                "through the prompt cache's pin/refcount discipline)")
        if tier_watermark < 0:
            raise ValueError(f"tier_watermark must be >= 0, got "
                             f"{tier_watermark}")
        self.model = model
        self.params = params
        self.slots = slots
        self.chunk_prefill = chunk_prefill
        self.decode_block = decode_block
        cfg = getattr(model.config, "base", model.config)
        self.max_seq = cfg.max_seq_len
        self.vocab = cfg.vocab_size
        # Multi-LoRA serving (models/lora.py MultiLoraDense): per-slot
        # adapter ids travel as a traced (B,) array, so requests on
        # DIFFERENT fine-tunes share the one decode program/batch. None
        # when the model has no adapter stacks — every core is then
        # called exactly as before (no recompile, no behavior change).
        self.n_adapters = getattr(cfg, "multi_lora", None)

        # Paged KV cache state (cfg doc in models/transformer.py; the
        # serving semantics live in this class's docstring above).
        if num_pages is not None and page_size is None:
            raise ValueError("num_pages needs page_size")
        self.paged = page_size is not None
        self.attn_backend = attn_backend
        if self.paged:
            if page_size < 1 or self.max_seq % page_size:
                raise ValueError(f"page_size {page_size} must divide "
                                 f"max_seq_len {self.max_seq}")
            self.page_size = page_size
            self.n_bt = self.max_seq // page_size  # block-table width
            if num_pages is None:
                num_pages = 1 + slots * self.n_bt  # dense parity + sink
            if num_pages < 2:
                raise ValueError(f"num_pages must be >= 2, got "
                                 f"{num_pages}")
            self.num_pages = num_pages
            self.pmodel = paged_model(model, num_pages=num_pages,
                                      page_size=page_size,
                                      attn_backend=attn_backend)
            self._alloc = _PageAllocator(num_pages)
            self._tables = np.zeros((slots, self.n_bt), np.int32)
            # Host mirror of every row's cache index — the injected
            # truth: each paged dispatch stamps it into the cache first,
            # making the device-side index disposable state.
            self._indices = np.zeros((slots,), np.int32)
            self._chains: "list[list[int]]" = [[] for _ in range(slots)]
            self._pinned: "dict[int, int]" = {}  # page -> #pcache pins

        # Host page tier (serve/tiering.py; loop thread only — HTTP
        # threads reach it through _TierCommand marshalling). _sessions
        # maps a session id to its chain's current pcache/tier key.
        self._tier = tier
        self.tier_watermark = tier_watermark
        self._sessions: "dict[str, tuple]" = {}

        # Speculative decoding state (loop thread only). _spec_hist[r]
        # is row r's prompt + every emitted token — the drafter's
        # lookup corpus; _spec_depth[r] is the row's adaptive proposal
        # budget in [1, spec_gamma].
        self.speculate = speculate
        self.spec_gamma = spec_gamma
        if speculate:
            from k3stpu.serve.speculative import NgramDrafter

            self._drafter = NgramDrafter()
            self._spec_hist: "list[list[int]]" = [[] for _ in range(slots)]
            self._spec_depth = np.full((slots,), spec_gamma, np.int32)

        # Decode-MFU model: one decoded token streams every weight
        # through the MXU once, ~2 flops per param (the standard
        # inference-MFU convention; attention's O(len·d) term is noise
        # next to the weight matmuls at serving batch sizes). Peak is
        # None off-TPU (CPU stand-in) — the MFU gauge then stays 0
        # rather than reporting a meaningless CPU ratio.
        from k3stpu.ops.matmul import peak_tflops_for

        self._decode_flops_per_tok = 2.0 * sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        peak = peak_tflops_for()
        self._peak_flops = None if peak is None else peak * 1e12

        self._cache = init_cache(self.pmodel if self.paged else model,
                                 slots)
        if self.paged:
            # Per-page HBM (all layers: K/V pools + int8 scale planes)
            # — the unit of the pcache byte accounting. Layout-aware:
            # pool leaves are identified BY NAME (`*_pages`, the same
            # rule every paged scatter uses), not by rank — an ndim
            # heuristic silently dropped the int8 pools' (P, ps, H)
            # fp32 scale planes from the count. Matches
            # models/quant.kv_page_bytes leaf for leaf (asserted in
            # tests/test_tiering.py).
            self._page_bytes = sum(
                v.nbytes // num_pages
                for p, v in
                jax.tree_util.tree_flatten_with_path(self._cache)[0]
                if str(getattr(p[-1], "key", "")).endswith("_pages"))
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def _cache_sharding(x):
                # (B, S, H, D) K/V and (B, S, H) scale leaves shard on
                # the head axis; (B,) index and anything indivisible
                # replicate.
                if x.ndim >= 3 and x.shape[2] % mesh.shape["model"] == 0:
                    return NamedSharding(mesh, P(None, None, "model"))
                return NamedSharding(mesh, P())

            self._cache = jax.tree.map(
                lambda x: jax.device_put(x, _cache_sharding(x)),
                self._cache)
        self._base_key = jax.random.key(seed)
        self._step_counter = 0

        # Host-side slot state (numpy: mutated only by the loop thread).
        self._active = np.zeros((slots,), bool)
        self._reserved = np.zeros((slots,), bool)  # chunked admission holds
        self._last_tok = np.zeros((slots,), np.int32)
        self._left = np.zeros((slots,), np.int64)
        self._temps = np.zeros((slots,), np.float32)
        self._topks = np.full((slots,), 1, np.int32)
        self._topps = np.ones((slots,), np.float32)
        self._eos = np.full((slots,), -1, np.int32)
        self._aids = np.zeros((slots,), np.int32)  # multi-LoRA slots
        self._owner: "list[_Request | None]" = [None] * slots
        self._collected: "list[list[int]]" = [[] for _ in range(slots)]

        # Admission bound: requests in flight (queued, admitting, or
        # decoding — counted from enqueue until the consumer returns).
        self.max_pending = max_pending
        self._inflight = 0  # guarded by _lock
        self._q: "queue.SimpleQueue[_Request | None]" = queue.SimpleQueue()
        self._pending: "list[_Request]" = []
        self._adm: "dict | None" = None  # in-flight chunked admission
        self._closed = False
        self._lock = threading.Lock()
        self._obs = obs
        self._stats = {"tokens": 0, "steps": 0, "dispatches": 0,
                       "busy_s": 0.0, "requests": 0,
                       "slot_occupancy_sum": 0.0, "peak_active_slots": 0,
                       "adm_chunks": 0,
                       "pcache_hits": 0, "pcache_prefix_hits": 0,
                       "pcache_misses": 0, "pcache_bytes": 0,
                       "rejected": 0,
                       # Speculative decoding (docs/SPECULATIVE.md):
                       # proposed/accepted drafts, emitted tokens and
                       # dispatches on the verify path, and iterations
                       # where a verify failure fell back to plain
                       # decode.
                       "spec_dispatches": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "spec_emitted": 0,
                       "spec_fallbacks": 0,
                       # Host page tier (docs/TIERING.md): admission
                       # probes that found / missed a tier chain,
                       # completed swap directions, and swaps that
                       # degraded to a cold prefill.
                       "tier_hits": 0, "tier_misses": 0,
                       "tier_swap_ins": 0, "tier_swap_outs": 0,
                       "tier_fallbacks": 0,
                       # Containment counters (docs/RESILIENCE.md).
                       "deadline_expired": 0, "watchdog_trips": 0,
                       "loop_crashes": 0, "loop_restarts": 0,
                       "breaker_rejected": 0}
        # Prompt cache: tuple(prompt tokens) -> (cache_1row, last_1row),
        # insertion-ordered dict as LRU (loop thread only).
        self.prompt_cache = prompt_cache
        self._pcache: "dict[tuple, tuple]" = {}

        # Containment state (docs/RESILIENCE.md). _waiters is every
        # client thread currently blocked on a request's event — the set
        # the watchdog fails with retryable errors when the loop stalls.
        self.breaker = breaker
        self._chaos = chaos
        self.watchdog_s = watchdog_s
        self._waiters: "set[_Request]" = set()  # guarded by _lock
        self._heartbeat = time.monotonic()  # stamped each loop iteration
        self._loop_exc: "BaseException | None" = None

        self._thread = threading.Thread(target=self._loop_main, daemon=True,
                                        name="generate-engine")
        self._thread.start()
        self._watchdog: "threading.Thread | None" = None
        self._wd_stop = threading.Event()
        if watchdog_s is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="engine-watchdog")
            self._watchdog.start()

    # --- jitted device programs (compiled once per static bucket) -------

    # params travel as jit ARGUMENTS (donated weights would bake into the
    # compiled program as constants otherwise — double the HBM). The
    # cache-model programs themselves are the shared cores in
    # serve/programs.py (one definition for engine + speculative).

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_step(self, params, cache, toks, temps, topks, topps,
                     step, base_key, aids=None):
        cache, logits = decode_core(self.model, params, cache, toks,
                                    adapter_ids=aids)
        key = jax.random.fold_in(base_key, step)
        return cache, _sample_rows(logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 9))
    def _decode_block_step(self, params, cache, toks, temps, topks,
                           topps, step, base_key, k_tokens: int,
                           aids=None):
        """K decode steps in ONE dispatch: ``lax.scan`` over the
        single-token core, sampling on-device each step. Returns the
        (K, B) token block; greedy rows are exactly K steps of argmax,
        so engine output stays pinned to ``generate()`` token for
        token. Rows that finish mid-block keep decoding (static shapes;
        the host discards their surplus) — their cache writes clamp at
        the row's last slot and the slot's next reuse scatters a fresh
        prefill over everything, index included."""
        block_key = jax.random.fold_in(base_key, step)

        def body(carry, i):
            cache, tok = carry
            cache, logits = decode_core(self.model, params, cache, tok,
                                        adapter_ids=aids)
            key = jax.random.fold_in(block_key, i)
            nxt = _sample_rows(logits, temps, topks, topps, key)
            return (cache, nxt), nxt

        (cache, _), out = jax.lax.scan(
            body, (cache, toks), jnp.arange(k_tokens))
        return cache, out

    @functools.partial(jax.jit, static_argnums=(0,))
    def _prefill(self, params, block, lens, aids=None):
        return prefill_core(self.model, params, block, lens,
                            adapter_ids=aids)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _scatter(self, big, small, slot_ids):
        return jax.tree.map(lambda b, s: b.at[slot_ids].set(s), big, small)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _extend_chunk(self, params, cache, chunk, aids=None):
        return extend_core(self.model, params, cache, chunk,
                           adapter_ids=aids)[0]

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_logits(self, params, cache, toks, aids=None):
        return decode_core(self.model, params, cache, toks,
                           adapter_ids=aids)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _first_sample(self, last_logits, temps, topks, topps, step,
                      base_key):
        key = jax.random.fold_in(base_key, step)
        return _sample_rows(last_logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _broadcast_rows(self, cache, last, n: int):
        """Row 0 of a 1-row admission cache replicated to n rows — the
        shared-prefix fan-out (one prefill, n sampled continuations)."""
        rep = jax.tree.map(
            lambda x: jnp.broadcast_to(x[:1], (n, *x.shape[1:])), cache)
        return rep, jnp.broadcast_to(last[:1], (n, *last.shape[1:]))

    # --- paged-cache programs (block tables + host-injected indices) ----

    # Every paged program takes the host's (slots,) index mirror and
    # stamps it into the cache before the core runs: device-side index
    # state is disposable, so a batch-wide call that advances OTHER
    # rows' indices (the prefix-hit extension neutralizes those rows
    # onto the sink page) is corrected for free at the next dispatch.
    # Block tables are traced int32 data — one compiled program serves
    # every page assignment, zero steady-state recompiles.

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_decode_step(self, params, cache, idx, bts, toks, temps,
                           topks, topps, step, base_key, aids=None):
        cache = set_cache_index(cache, idx)
        cache, logits = decode_core(self.pmodel, params, cache, toks,
                                    adapter_ids=aids, block_tables=bts)
        key = jax.random.fold_in(base_key, step)
        return cache, _sample_rows(logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 11))
    def _paged_decode_block_step(self, params, cache, idx, bts, toks,
                                 temps, topks, topps, step, base_key,
                                 k_tokens: int, aids=None):
        cache = set_cache_index(cache, idx)
        block_key = jax.random.fold_in(base_key, step)

        def body(carry, i):
            cache, tok = carry
            cache, logits = decode_core(self.pmodel, params, cache, tok,
                                        adapter_ids=aids,
                                        block_tables=bts)
            key = jax.random.fold_in(block_key, i)
            nxt = _sample_rows(logits, temps, topks, topps, key)
            return (cache, nxt), nxt

        (cache, _), out = jax.lax.scan(
            body, (cache, toks), jnp.arange(k_tokens))
        return cache, out

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_extend(self, params, cache, idx, bts, chunk, aids=None):
        cache = set_cache_index(cache, idx)
        return extend_core(self.pmodel, params, cache, chunk,
                           adapter_ids=aids, block_tables=bts)[0]

    @functools.partial(jax.jit, static_argnums=(0,))
    def _paged_decode_logits(self, params, cache, idx, bts, toks,
                             aids=None):
        cache = set_cache_index(cache, idx)
        return decode_core(self.pmodel, params, cache, toks,
                           adapter_ids=aids, block_tables=bts)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _spec_verify(self, params, cache, idx, bts, chunk, aids=None):
        """Speculative verify: ONE extend over the static
        ``(slots, spec_gamma+1)`` chunk ``[x0, d1..d_gamma]``.
        ``logits[:, j]`` scores the token after ``chunk[:, :j+1]``, so
        the row-wise argmax is the target's own greedy continuation at
        every draft position — the host keeps each row's longest
        matching prefix plus the token at the first divergence. The
        argmax epilogue stays in-jit (shipping (slots, G, V) logits to
        the host every dispatch would swamp the win) and is also what
        pins ``speculate=True`` to greedy exactness: there is no
        sampled verify."""
        cache = set_cache_index(cache, idx)
        cache, logits = extend_core(self.pmodel, params, cache, chunk,
                                    adapter_ids=aids, block_tables=bts)
        return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _pack_pages(self, pool, small, page_map):
        """Scatter a dense-prefilled admission cache into the page pool:
        row j's (max_seq,) K/V reshapes into (n_bt, page_size) pages and
        lands at pages ``page_map[j]`` (pad rows map to the sink). One
        compile per admitted-rows bucket; 'index' leaves pass through —
        they are host-injected at every dispatch."""
        dense = {tuple(k.key for k in p): v for p, v
                 in jax.tree_util.tree_flatten_with_path(small)[0]}

        def pack(path, leaf):
            name = path[-1].key
            if not name.endswith("_pages"):
                return leaf
            src = dense[tuple(k.key for k in path[:-1])
                        + (name[:-len("_pages")],)]
            r = src.reshape(src.shape[0], -1, self.page_size,
                            *src.shape[2:])
            return leaf.at[page_map].set(r)

        return jax.tree_util.tree_map_with_path(pack, pool)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _copy_page(self, pool, src, dst):
        """Duplicate ONE page across every layer's pool — the
        copy-on-write behind prefix sharing (a partial tail page gets
        written by its row, so sharers take a private copy). src/dst
        trace: every copy reuses one compiled program."""
        return jax.tree_util.tree_map_with_path(
            lambda p, x: (x.at[dst].set(x[src])
                          if str(getattr(p[-1], "key", "")
                                 ).endswith("_pages") else x),
            pool)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _restore_pages(self, pool, host, page_idx):
        """Tier swap-in scatter: host-gathered page rows (a dict keyed
        by "/"-joined leaf paths, each ``(n, page_size, ...)``) land at
        pages ``page_idx`` across every ``*_pages`` pool leaf in ONE
        dispatch — jit turns the host dict into a single batched
        device_put + scatter. ``n`` is pow2-bucketed by the caller; pad
        rows carry zeros and target the sink page 0 (which absorbs junk
        writes by design), so one compile serves every chain length in
        a bucket."""
        def put(path, leaf):
            if not str(getattr(path[-1], "key", "")).endswith("_pages"):
                return leaf
            key = "/".join(str(getattr(k, "key", k)) for k in path)
            return leaf.at[page_idx].set(host[key])

        return jax.tree_util.tree_map_with_path(put, pool)

    # --- prompt cache (loop thread only; entries are immutable jax
    #     arrays, so a cached row survives the decodes of whatever slot
    #     its copy was scattered into) ------------------------------------

    def _pcache_lookup(self, prompt: tuple, adapter: int = 0):
        """Longest cached entry equal to ``prompt`` or a proper prefix of
        it, UNDER THE SAME ADAPTER (a row prefilled through adapter i's
        deltas is a different computation — cross-adapter reuse would be
        silently wrong); a hit refreshes its LRU position. Returns the
        PROMPT part of the key. Session-tail entries (logits slot None —
        the chain a finished session left behind covers prompt+reply
        K/V but no next-token distribution) only ever serve as PREFIX
        hits: an exact-length match would need the stored logits the
        entry doesn't have, so it is skipped and the shorter
        logits-bearing entry (or a miss) wins instead."""
        best = None
        for aid, key in self._pcache:
            if (aid == adapter and len(key) <= len(prompt)
                    and prompt[:len(key)] == key
                    and not (len(key) == len(prompt)
                             and self._pcache[(aid, key)][-2] is None)
                    and (best is None or len(key) > len(best))):
                best = key
        if best is None:
            return None, None
        entry = self._pcache.pop((adapter, best))  # re-insert at MRU
        self._pcache[(adapter, best)] = entry
        return best, entry

    def _pcache_insert(self, prompt: tuple, cache1, last1,
                       adapter: int = 0) -> None:
        if self.prompt_cache <= 0:
            return
        old = self._pcache.pop((adapter, prompt), None)
        nbytes = sum(x.nbytes for x in jax.tree.leaves((cache1, last1)))
        self._pcache[(adapter, prompt)] = (cache1, last1, nbytes)
        delta = nbytes - (old[2] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] = (
                self._stats.get("pcache_bytes", 0) + delta)

    def _pcache_extend(self, cache1, prompt: tuple, p0: int,
                       adapter: int = 0):
        """Append ``prompt[p0:]`` to a restored 1-row cache (row index sits
        at p0). Returns (cache, last_logits) in EXACTLY the post-prefill
        state: the suffix pads to a pow2 chunk, the index rolls back to
        len-1 (pad junk becomes invisible to the position mask, the
        chunked-admission finalize invariant) and the last real token is
        re-decoded in place for the exact first-token logits."""
        extra = np.asarray(prompt[p0:], np.int32)[None]
        g = _pow2_at_least(extra.shape[1])
        pad = np.zeros((1, g), np.int32)
        pad[:, :extra.shape[1]] = extra
        aids = self._aid_arg(1, adapter)
        cache = self._extend_chunk(self.params, cache1, jnp.asarray(pad),
                                   aids)
        cache = set_cache_index(
            cache, jnp.asarray([len(prompt) - 1], jnp.int32))
        return self._decode_logits(
            self.params, cache, jnp.asarray([prompt[-1]], jnp.int32), aids)

    # --- page-chain bookkeeping (paged mode; loop thread only) ----------

    def _pages_for(self, length: int, budget: int) -> int:
        return -(-(length + budget) // self.page_size)  # ceil div

    def _set_row(self, r: int, chain, index: int) -> None:
        self._chains[r] = list(chain)
        self._tables[r, :] = 0
        self._tables[r, :len(chain)] = chain
        self._indices[r] = index

    def _release_slot_pages(self, r: int) -> None:
        if self._chains[r]:
            self._alloc.decref(self._chains[r])
        self._chains[r] = []
        self._tables[r, :] = 0

    def _free_chains(self, chains) -> None:
        for c in chains or []:
            if c:
                self._alloc.decref(c)

    def _pages_needed(self, req: "_Request", pkey) -> int:
        """Worst-case fresh pages this admission will allocate — the fit
        check, run BEFORE any device work or allocation. Mirrors the
        alloc paths exactly: cache hits only pay for non-shared pages."""
        ps, B = self.page_size, req.budget
        n = req.samples if req.samples > 1 else req.block.shape[0]
        # +1: a single-prompt admission pins a COW tail copy into the
        # prompt cache (the insert skips gracefully when the pool is
        # dry, but reserving it keeps the pin from stealing a page a
        # sibling row's chain already counted on).
        ins = 1 if (self.prompt_cache > 0
                    and req.block.shape[0] == 1) else 0
        if pkey is not None:
            L = len(req.ptuple())
            total = self._pages_for(L, B)
            if len(pkey) == L:  # exact hit: no insert afterwards
                return n * (total - len(pkey) // ps)
            # prefix: row 0 shares the entry, siblings share row 0
            return (total - len(pkey) // ps
                    + (n - 1) * (total - L // ps) + ins)
        if req.samples > 1:
            L = int(req.lens[0])
            total = self._pages_for(L, B)
            return total + (n - 1) * (total - L // ps) + ins
        return sum(self._pages_for(int(l), B)
                   for l in req.lens) + (ins if n == 1 else 0)

    def _alloc_request_chains(self, req: "_Request", nb: int, n: int,
                              lens) -> "list[list[int]]":
        """Fresh page chains for a dense-prefilled admission, one list
        per real row (pad rows get []). samples>1 allocates the full
        chain for row 0 only — siblings get just their non-shared pages
        (install increfs the shared prefix into their chains)."""
        B = req.budget
        if self._chaos is not None:
            self._chaos.fire("page_alloc")
        if req.samples > 1:
            L = int(lens[0])
            total = self._pages_for(L, B)
            want = [total] + [total - L // self.page_size] * (n - 1)
        else:
            want = [self._pages_for(int(lens[j]), B) for j in range(n)]
        chains = []
        for w in want:
            c = self._alloc.alloc(w)
            if c is None:  # can't happen after the fit check; roll back
                self._free_chains(chains)
                raise RuntimeError("page pool exhausted mid-admission")
            chains.append(c)
        return chains + [[] for _ in range(nb - n)]

    def _pin_pages(self, chain) -> None:
        for p in chain:
            self._pinned[p] = self._pinned.get(p, 0) + 1

    def _unpin_pages(self, chain) -> None:
        for p in chain:
            left = self._pinned[p] - 1
            if left:
                self._pinned[p] = left
            else:
                del self._pinned[p]

    def _pcache_evict_lru(self, swap: bool = True) -> int:
        """Drop the LRU prompt-cache entry (paged entries release their
        page pins); returns its byte size. Caller adjusts the stat.
        With a host tier attached the entry's chain is GATHERED off
        device first (``swap=False`` skips that — crash paths where
        device state is untrusted), so eviction demotes instead of
        forgetting; a failed gather falls back to the plain drop."""
        key = next(iter(self._pcache))
        entry = self._pcache.pop(key)
        if self.paged:
            if swap and self._tier is not None:
                self._tier_swap_out(key, entry)
            self._unpin_pages(entry[0])
            self._alloc.decref(entry[0])
        return entry[-1]

    def _pcache_insert_paged(self, prompt: tuple, src_chain, last1,
                             adapter: int = 0,
                             frozen: bool = False) -> None:
        """Pin ``prompt``'s pages into the prompt cache WITHOUT copying
        the prompt K/V: the entry shares the source row's full pages by
        incref — safe read-only, since a row only ever writes positions
        >= its admitted length, which live past its full prompt pages —
        and copies only the partial tail page (the row's next decode
        DOES write into that one). Skipped when the pool can't spare
        the tail copy.

        ``frozen``: the source row is FINISHED (session-end insert) —
        nothing will ever write its tail page again, so the partial
        tail is shared by incref like the full pages instead of COW
        copied (a later admission that extends the entry takes its own
        tail copy through ``build_row``, same as any prefix hit). Saves
        one page + one device copy per session turn, and cannot fail on
        an exhausted pool."""
        if self.prompt_cache <= 0:
            return
        ps = self.page_size
        full = len(prompt) // ps
        chain = list(src_chain[:full])
        self._alloc.incref(chain)
        if len(prompt) % ps:
            if frozen:
                chain.append(src_chain[full])
                self._alloc.incref(chain[-1:])
            else:
                tail = self._alloc.alloc(1)
                if tail is None:
                    self._alloc.decref(chain)
                    return  # pool too tight to pin a copy — skip caching
                self._cache = self._copy_page(self._cache,
                                              src_chain[full], tail[0])
                chain.append(tail[0])
        old = self._pcache.pop((adapter, prompt), None)
        if old is not None:
            self._unpin_pages(old[0])
            self._alloc.decref(old[0])
        self._pin_pages(chain)
        nbytes = len(chain) * self._page_bytes \
            + (sum(x.nbytes for x in jax.tree.leaves(last1))
               if last1 is not None else 0)
        self._pcache[(adapter, prompt)] = (tuple(chain), len(prompt),
                                           last1, nbytes)
        delta = nbytes - (old[-1] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] += delta

    # --- host page tier (docs/TIERING.md; loop thread only) -------------

    def _gather_pages(self, chain) -> dict:
        """One host copy of a page chain: every ``*_pages`` pool leaf
        gathered at the chain's indices, fetched in a SINGLE
        ``jax.device_get`` of the whole dict (one transfer round-trip,
        not one per layer). Keys are the "/"-joined leaf paths —
        exactly what ``_restore_pages`` scatters back from."""
        idx = jnp.asarray(chain, jnp.int32)
        out = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                self._cache)[0]:
            if str(getattr(path[-1], "key", "")).endswith("_pages"):
                key = "/".join(str(getattr(k, "key", k)) for k in path)
                out[key] = leaf[idx]
        return jax.device_get(out)

    def _tier_swap_out(self, key, entry) -> bool:
        """Gather a pcache entry's chain to the host tier. The caller
        still owns the entry (and drops its pins/refs afterwards) —
        this only copies bytes off device, so a failure (chaos
        ``tier_swap``, host OOM) simply leaves the entry to die the
        pre-tier way: dropped, next turn pays a cold prefill. Entry
        pages are immutable once inserted (COW discipline), so the
        gather needs no quiescence even while live rows share the
        chain's full pages."""
        t0 = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("tier_swap")
            host = self._gather_pages(entry[0])
            last = entry[2]
            if last is not None:
                last = jax.device_get(last)
            self._tier.put(key, entry[1], host, last=last)
        except Exception:  # noqa: BLE001 — degrade to plain eviction
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        dt = time.perf_counter() - t0
        with self._lock:
            self._stats["tier_swap_outs"] += 1
        if self._obs is not None:
            self._obs.on_tier_swap(
                "out", dt, self._tier.stats()["tier_pages"],
                self._alloc.total - self._alloc.free)
        return True

    def _tier_swap_in(self, key) -> bool:
        """Restore a tier entry into the prompt cache: allocate fresh
        pages (pressure-evicting idle pcache entries first), scatter
        the host buffers in via one ``_restore_pages`` dispatch, pin +
        insert — after which the entry serves hits exactly like one
        that never left. FRESH pages only: no live row's table points
        at them, so any failure rolls back by freeing them — live rows
        are untouchable by construction. Failure paths degrade to a
        cold prefill (``tier_fallbacks``); corrupt/undecodable entries
        are discarded so they cannot fail every later probe too."""
        t0 = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("tier_swap")
            length, host, last = self._tier.load(key)
        except Exception:  # noqa: BLE001 — torn spill / injected fault
            self._tier.discard(key)
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        n = -(-length // self.page_size)
        while n > self._alloc.free and self._pcache:
            freed = self._pcache_evict_lru()
            with self._lock:
                self._stats["pcache_bytes"] -= freed
        pages = self._alloc.alloc(n)
        if pages is None:
            # Pool too tight even after pressure: keep the host copy
            # (it is still good — a later, calmer admission can restore
            # it) and let THIS request prefill cold.
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        try:
            npad = _pow2_at_least(n)
            idx = np.zeros((npad,), np.int32)
            idx[:n] = pages
            hpad = {}
            for k, v in host.items():
                buf = np.zeros((npad,) + v.shape[1:], v.dtype)
                buf[:n] = v[:n]
                hpad[k] = buf
            self._cache = self._restore_pages(self._cache, hpad,
                                              jnp.asarray(idx))
            last_dev = jnp.asarray(last) if last is not None else None
        except Exception:  # noqa: BLE001 — restore dispatch failed
            self._record_backend_failure()
            self._alloc.decref(pages)
            self._tier.discard(key)
            with self._lock:
                self._stats["tier_fallbacks"] += 1
            if self._obs is not None:
                self._obs.on_tier_fallback()
            return False
        self._pin_pages(pages)
        old = self._pcache.pop(key, None)
        if old is not None:  # raced a fresh insert; replace it
            self._unpin_pages(old[0])
            self._alloc.decref(old[0])
        nbytes = n * self._page_bytes \
            + (int(last_dev.nbytes) if last_dev is not None else 0)
        self._pcache[key] = (tuple(pages), length, last_dev, nbytes)
        delta = nbytes - (old[-1] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            delta -= self._pcache_evict_lru()
        with self._lock:
            self._stats["pcache_bytes"] += delta
            self._stats["tier_swap_ins"] += 1
        self._tier.discard(key)  # moved, not copied: one owner at a time
        if self._obs is not None:
            self._obs.on_tier_swap(
                "in", time.perf_counter() - t0,
                self._tier.stats()["tier_pages"],
                self._alloc.total - self._alloc.free)
        return True

    def _tier_pressure(self) -> None:
        """Low-watermark demotion, run once per loop iteration: while
        the free list sits below ``tier_watermark`` and idle pcache
        entries exist, gather the LRU entry to host and return its
        pages. Terminates because each pass shrinks the pcache;
        entries whose pages are shared with live rows free only their
        unshared pages (refcounts), which is exactly the reclaimable
        amount."""
        while (self._alloc.free < self.tier_watermark and self._pcache):
            freed = self._pcache_evict_lru()
            with self._lock:
                self._stats["pcache_bytes"] -= freed

    def _session_insert(self, req: "_Request", r: int) -> None:
        """Session-end insert (called from _finish_row BEFORE the row's
        pages are released): pin the finished row's chain into the
        prompt cache keyed by prompt + every reply token except the
        last. That key is exactly the K/V the chain holds — after g
        emitted tokens the row's index is L+g-1 and positions
        L..L+g-2 hold t1..t_{g-1}; the last sampled token's K/V was
        never written (and any mid-block post-eos junk lies beyond the
        key length, invisible to the position mask). The entry stores
        last=None — no logits exist for the uncommitted tail token —
        so it serves prefix hits only (the next turn's prompt strictly
        extends it through t_g). The session's previous chain is
        dropped from pcache AND tier: one chain per session. A
        one-token turn adopts the admission-time exact-prompt entry
        (same key, better: it has logits) rather than inserting."""
        toks = self._collected[r]
        if len(toks) < 2:
            # One-token turn: the key (prompt + zero committed reply
            # tokens) IS the prompt, and admission already cached that
            # exact chain WITH its next-token logits. Inserting a
            # frozen last=None twin would replace the strictly better
            # entry — adopt the existing one into the ledger instead,
            # so release_session parks the live chain, not the
            # previous turn's stale key.
            key = (req.adapter, req.ptuple())
            if key not in self._pcache:
                return  # evicted (or never inserted); keep prev chain
        else:
            key_prompt = req.ptuple() + tuple(toks[:-1])
            n_entry = -(-len(key_prompt) // self.page_size)
            chain = self._chains[r]
            if len(chain) < n_entry:  # defensive: never by allocation
                return
            self._pcache_insert_paged(key_prompt, chain[:n_entry], None,
                                      req.adapter, frozen=True)
            key = (req.adapter, key_prompt)
            if key not in self._pcache:
                return  # capacity-evicted immediately; nothing to track
        prev = self._sessions.get(req.session)
        if prev is not None and prev != key:
            ent = self._pcache.pop(prev, None)
            if ent is not None:
                self._unpin_pages(ent[0])
                self._alloc.decref(ent[0])
                with self._lock:
                    self._stats["pcache_bytes"] -= ent[-1]
            if self._tier is not None:
                self._tier.discard(prev)
        self._sessions[req.session] = key

    def _do_release_session(self, session: str,
                            spill: bool = False) -> bool:
        """Loop-thread body of release_session: demote the session's
        pcache entry to the host tier (gather + unpin + free pages).
        True when a chain existed (now on host — or already there).
        ``spill`` additionally forces the parked chain to the disk tier
        (no-op without --tier-dir): the drain path, where the chain
        must outlive this process for a peer replica to adopt it."""
        key = self._sessions.get(session)
        if key is None:
            return False
        entry = self._pcache.pop(key, None)
        if entry is None:
            # Already demoted (watermark pressure / LRU eviction beat
            # the explicit release to it).
            had = self._tier is not None and self._tier.contains(key)
            if had and spill:
                self._tier.spill(key)
            return had
        if self._tier is not None:
            if self._tier_swap_out(key, entry) and spill:
                self._tier.spill(key)
        self._unpin_pages(entry[0])
        self._alloc.decref(entry[0])
        with self._lock:
            self._stats["pcache_bytes"] -= entry[-1]
        return True

    def release_session(self, session: str,
                        timeout_s: float = 30.0,
                        spill: bool = False) -> bool:
        """Explicitly park a session between turns: its cached chain
        leaves the device pool for the host tier (or is dropped when no
        tier is attached) and the freed pages go back to admission.
        ``spill=True`` forces the parked chain through to the disk tier
        so it survives this process (drain-before-kill; requires
        --tier-dir to have any effect). Safe from any thread — the
        operation marshals to the loop thread via the request queue.
        Returns whether the session had a chain to release."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not self.paged:
            return False
        cmd = _TierCommand("release", session, spill=spill)
        self._q.put(cmd)
        if not cmd.event.wait(timeout_s):
            raise TimeoutError("session release did not finish in time")
        if cmd.error is not None:
            raise cmd.error
        return bool(cmd.result)

    def _exec_tier_command(self, cmd: "_TierCommand") -> None:
        try:
            if cmd.kind == "release":
                cmd.result = self._do_release_session(cmd.session,
                                                      spill=cmd.spill)
            else:  # unknown kinds fail loudly, never hang the caller
                raise ValueError(f"unknown tier command {cmd.kind!r}")
        except Exception as e:  # noqa: BLE001 — fail the one command
            cmd.error = e
        cmd.signal()

    def _aid_arg(self, n: int, adapter: int):
        """(n,)-row adapter-id array for a single request's device call —
        None when the model carries no adapter stacks (exact pre-multi-
        LoRA program signatures)."""
        if self.n_adapters is None:
            return None
        return jnp.full((n,), adapter, jnp.int32)

    # --- client API -----------------------------------------------------

    def _packed_request(self, prompts, max_new_tokens, temperature, top_k,
                        eos_id, samples=1, top_p=None,
                        adapter_id=0) -> "_Request":
        """Shared validation + packing for both entry points: right-pad to
        a pow2 width bucket and bound against the cache."""
        adapter_id = int(adapter_id)
        if adapter_id != 0 and self.n_adapters is None:
            raise ValueError("this engine's model has no adapter stacks "
                             "(multi_lora is off); adapter_id must be 0")
        if self.n_adapters is not None \
                and not 0 <= adapter_id < self.n_adapters:
            raise ValueError(f"adapter_id {adapter_id} outside "
                             f"[0, {self.n_adapters})")
        lens = [len(p) for p in prompts]
        if min(lens) == 0:
            raise ValueError("prompts must be non-empty")
        width = prompt_width_bucket(max(lens), self.max_seq)
        if max(lens) > width or width + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {max(lens)} + budget {max_new_tokens} exceeds the "
                f"cache ({self.max_seq})")
        if self.paged:
            # A request whose WORST-CASE page need (no cache sharing)
            # exceeds the pool would wait in the queue forever — reject
            # at the door instead of deadlocking admission.
            ps = self.page_size
            if samples > 1:
                total = self._pages_for(lens[0], max_new_tokens)
                worst = total + (samples - 1) * (total - lens[0] // ps)
            else:
                worst = sum(self._pages_for(l, max_new_tokens)
                            for l in lens)
            ins = 1 if (self.prompt_cache > 0 and len(prompts) == 1) else 0
            if worst + ins > self._alloc.total:
                raise ValueError(
                    f"request needs up to {worst + ins} pages but the "
                    f"pool has {self._alloc.total} usable — raise "
                    f"num_pages or shrink prompt/budget")
        block = np.zeros((len(prompts), width), np.int32)
        for i, p in enumerate(prompts):
            block[i, :len(p)] = p
        return _Request(block, np.asarray(lens, np.int32), max_new_tokens,
                        float(temperature), top_k, eos_id, samples=samples,
                        top_p=top_p, adapter=adapter_id)

    def _reject_if_full_locked(self) -> None:
        """Caller holds self._lock. Raises EngineOverloaded (counted in
        the rejected stat) when max_pending is exhausted."""
        if (self.max_pending is not None
                and self._inflight >= self.max_pending):
            self._stats["rejected"] += 1
            raise EngineOverloaded(
                f"engine at capacity: {self._inflight} requests in "
                f"flight (max_pending={self.max_pending})")

    def _breaker_gate(self) -> bool:
        """Circuit-breaker admission gate. Returns True when this caller
        holds the half-open probe lease; raises CircuitOpen (counted in
        breaker_rejected) when the breaker refuses traffic."""
        br = self.breaker
        if br is None:
            return False
        admitted, probe = br.allow()
        if not admitted:
            retry = br.retry_after_s()
            with self._lock:
                self._stats["breaker_rejected"] += 1
            raise CircuitOpen(
                f"circuit breaker open after repeated backend failures; "
                f"retry in {retry:.1f}s", retry_after_s=retry)
        return probe

    def take_admission_token(self) -> None:
        """Claim one unit of max_pending or raise EngineOverloaded.
        Callers that split ONE logical request into several chunk
        submits (the server's wider-than-slots path) take ONE token for
        the whole request and pass ``admitted=True`` to the submits —
        re-gating per chunk would reject an already-admitted request
        mid-flight after burning its earlier chunks' decode work."""
        probe = self._breaker_gate()
        try:
            with self._lock:
                self._reject_if_full_locked()
                self._inflight += 1
        except EngineOverloaded:
            if probe:
                # The half-open probe lost the capacity race before
                # reaching the backend — return the lease so the next
                # arrival can probe instead of waiting out the window.
                self.breaker.probe_aborted()
            raise

    def release_admission_token(self) -> None:
        with self._lock:
            self._inflight -= 1

    def at_capacity(self) -> bool:
        """Advisory (racy by nature): lets the HTTP layer 503 BEFORE
        committing response headers; the authoritative check is the
        token take in the submit paths."""
        with self._lock:
            return (self.max_pending is not None
                    and self._inflight >= self.max_pending)

    def reject_if_at_capacity(self) -> None:
        """Advisory shed WITHOUT claiming a token: raises
        EngineOverloaded (counted in the rejected stat, same as an
        authoritative take failure) when at capacity. For callers that
        must 503 before response headers but defer the real token take
        until their generator actually starts."""
        br = self.breaker
        if br is not None and br.state() == "open":
            retry = br.retry_after_s()
            with self._lock:
                self._stats["breaker_rejected"] += 1
            raise CircuitOpen(
                f"circuit breaker open after repeated backend failures; "
                f"retry in {retry:.1f}s", retry_after_s=retry)
        with self._lock:
            self._reject_if_full_locked()

    def _trace_enqueue(self, req: "_Request", stream: bool = False) -> None:
        """Open the request's lifecycle trace at ingress (submitter
        thread, just before the queue put — so queue wait is measured
        from the moment the loop COULD have seen the request)."""
        if self._obs is not None:
            req.trace = self._obs.start_trace(
                trace_id=req.trace_id,
                rows=int(req.samples if req.samples > 1
                         else req.block.shape[0]),
                prompt_len=int(max(req.lens)), budget=int(req.budget),
                stream=stream, adapter=int(req.adapter))

    def _enqueue_and_wait(self, req: "_Request", timeout_s: float,
                          admitted: bool = False) -> "list[list[int]]":
        # The loop thread enforces the same deadline: a request whose
        # client gave up is dropped from the queue / its slots freed,
        # instead of decoding its full budget for nobody.
        if not admitted:
            self.take_admission_token()
        try:
            req.deadline = time.time() + timeout_s
            self._trace_enqueue(req)
            # Waiter registry: the watchdog fails everyone in this set
            # with a retryable error when the loop stalls or dies, so a
            # client blocks for at most ~watchdog_s, never timeout_s.
            with self._lock:
                self._waiters.add(req)
            try:
                self._q.put(req)
                if not req.event.wait(timeout_s + 1.0):
                    raise TimeoutError("generation did not finish in time")
                if req.error is not None:
                    raise req.error
                return req.tokens
            finally:
                with self._lock:
                    self._waiters.discard(req)
        finally:
            if not admitted:
                self.release_admission_token()

    def submit(self, prompts: "list[list[int]]", *, max_new_tokens: int,
               temperature: float = 0.0, top_k: "int | None" = None,
               top_p: "float | None" = None,
               eos_id: "int | None" = None, adapter_id: int = 0,
               timeout_s: float = 600.0, admitted: bool = False,
               trace_id: "str | None" = None,
               session: "str | None" = None) -> "list[list[int]]":
        """Blocking: returns (n, max_new_tokens) token lists.
        ``admitted``: the caller already holds an admission token
        covering this submit (see take_admission_token).
        ``trace_id``: validated W3C trace id for the lifecycle trace.
        ``session``: single-prompt only — names the request's finished
        KV chain so the session's next turn (a prompt extending this
        one's prompt + reply) restores it instead of re-prefilling,
        and so ``release_session`` can park it on the host tier."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        if session is not None and n != 1:
            raise ValueError("session requires exactly one prompt "
                             "(a session names ONE chain)")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        req.session = session
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_samples(self, prompt: "list[int]", n: int, *,
                       max_new_tokens: int, temperature: float = 1.0,
                       top_k: "int | None" = None,
                       top_p: "float | None" = None,
                       eos_id: "int | None" = None, adapter_id: int = 0,
                       timeout_s: float = 600.0, admitted: bool = False,
                       trace_id: "str | None" = None) -> "list[list[int]]":
        """n sampled continuations of ONE prompt for the price of one
        prefill: the prefilled cache row broadcasts across n slots and the
        rows diverge through per-row sampling noise. (With temperature 0
        all rows are the same greedy continuation — use submit().)"""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not 1 <= n <= self.slots:
            raise ValueError(f"need 1..{self.slots} samples, got {n}")
        req = self._packed_request([prompt], max_new_tokens, temperature,
                                   top_k, eos_id, samples=n, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_stream(self, prompts: "list[list[int]]", *,
                      max_new_tokens: int, temperature: float = 0.0,
                      top_k: "int | None" = None,
                      top_p: "float | None" = None,
                      eos_id: "int | None" = None, adapter_id: int = 0,
                      timeout_s: float = 600.0, admitted: bool = False,
                      trace_id: "str | None" = None,
                      session: "str | None" = None):
        """Streaming submit(): returns an iterator of events.

        Incremental events are ``{"done": False, "rows": {row: [tok, ...]}}``
        — one per decode dispatch that produced tokens for this request
        (granularity = ``decode_block``; the first event carries each
        row's first token straight off the prefill logits, so
        time-to-first-token is prefill latency). The final event is
        ``{"done": True, "tokens": [[...]]}`` with exactly submit()'s
        return value (greedy exactness stays pinned to ``generate()``).
        Rows that hit eos stop producing deltas; the final tokens are
        eos-extended to the budget like submit()'s. Errors (deadline
        expiry, decode failure, shutdown) raise from the iterator."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        if session is not None and n != 1:
            raise ValueError("session requires exactly one prompt "
                             "(a session names ONE chain)")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        req.trace_id = trace_id
        req.session = session
        req.stream_q = queue.SimpleQueue()
        return self._stream_events(req, timeout_s, admitted)

    def _stream_events(self, req: "_Request", timeout_s: float,
                       admitted: bool = False):
        # Same deadline contract as _enqueue_and_wait: the loop thread
        # drops expired requests; this consumer gets the terminal marker
        # and raises the TimeoutError the loop recorded. The admission
        # token spans the generator's life — taken at first next() (no
        # iteration, no enqueue, no token), released in the finally.
        if not admitted:
            self.take_admission_token()
        try:
            yield from self._stream_events_inner(req, timeout_s)
        finally:
            if not admitted:
                self.release_admission_token()

    def _stream_events_inner(self, req: "_Request", timeout_s: float):
        req.deadline = time.time() + timeout_s
        self._trace_enqueue(req, stream=True)
        with self._lock:
            self._waiters.add(req)
        self._q.put(req)
        hard = req.deadline + 1.0
        try:
            while True:
                try:
                    item = req.stream_q.get(
                        timeout=max(0.0, hard - time.time()))
                except queue.Empty:
                    raise TimeoutError("generation did not finish in time")
                if item is None:  # terminal: tokens ready or error
                    if req.error is not None:
                        raise req.error
                    yield {"done": True, "tokens": req.tokens}
                    return
                yield {"done": False, "rows": item}
        finally:
            with self._lock:
                self._waiters.discard(req)
            # Consumer abandoned the stream (generator .close() on client
            # disconnect, or an exception in the consumer): expire the
            # request NOW so the loop reaps its queue entry / admission /
            # slots next iteration, instead of decoding the rest of the
            # budget for nobody.
            if req.tokens is None and req.error is None:
                req.deadline = 0.0

    def close(self) -> None:
        self._closed = True
        self._wd_stop.set()
        self._q.put(None)
        self._thread.join(timeout=60)
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)

    def loop_alive(self) -> bool:
        """Liveness of the engine loop thread (the server's /healthz
        consults this; the watchdog revives a dead loop, so not-alive is
        a transient not-ready, not a terminal state)."""
        return self._thread.is_alive()

    def reset_stats(self) -> None:
        """Zero the counters (post-warmup: compile-dominated dispatches
        would poison the reported tokens_per_s). pcache_bytes is live
        state, not a counter — it survives the reset."""
        with self._lock:
            keep = self._stats["pcache_bytes"]
            for k in self._stats:
                self._stats[k] = type(self._stats[k])()
            self._stats["pcache_bytes"] = keep
        if self._obs is not None:
            self._obs.reset()

    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
        s["tokens_per_s"] = (round(s["tokens"] / s["busy_s"], 2)
                             if s["busy_s"] > 0 else None)
        s["avg_active_slots"] = (round(s["slot_occupancy_sum"] / s["steps"],
                                       2) if s["steps"] else None)
        s["pcache_entries"] = len(self._pcache)
        s["attn_backend"] = self.attn_backend
        if self.breaker is not None:
            s["breaker_state"] = self.breaker.state()
            s["breaker_trips"] = self.breaker.trips
        if self.paged:
            total, free = self._alloc.total, self._alloc.free
            s["pages_total"] = total
            s["pages_free"] = free
            s["pages_resident"] = total - free
            s["pages_pinned"] = len(self._pinned)
            if self._tier is not None:
                ts = self._tier.stats()
                s["host_tier_pages"] = ts.pop("tier_pages")
                s.update(ts)
                s["sessions_tracked"] = len(self._sessions)
            s["page_utilization"] = round((total - free) / total, 4)
            # Pinned pages with >1 reference ARE the zero-copy sharing:
            # mapped read-only into a live row's table, or claimed by
            # several cache entries (an extended prompt shares its
            # ancestor's full pages).
            s["pcache_shared_pages"] = sum(
                1 for p in list(self._pinned)
                if self._alloc.refcount(p) > 1)
            # Token-slots a dense cache needs for this many slots vs
            # what the pool actually holds — the measured density
            # multiplier (> 1: same slot count in less HBM).
            s["paged_density_ratio"] = round(
                self.slots * self.max_seq / (total * self.page_size), 2)
        if self.speculate:
            s["spec_accept_rate"] = (
                round(s["spec_accepted"] / s["spec_proposed"], 4)
                if s["spec_proposed"] else None)
            s["spec_tokens_per_dispatch"] = (
                round(s["spec_emitted"] / s["spec_dispatches"], 2)
                if s["spec_dispatches"] else None)
        return s

    # --- loop internals (single thread; owns all slot state) ------------

    def _decode_mfu(self, tokens: int, dt: float) -> "float | None":
        """Modeled MFU of one decode dispatch: emitted tokens × modeled
        flops/token over measured wall time, against the device peak.
        None when the peak is unknown (CPU stand-in) or dt is zero."""
        if self._peak_flops is None or dt <= 0:
            return None
        return tokens * self._decode_flops_per_tok / dt / self._peak_flops

    def _free_slots(self) -> "list[int]":
        # A row that finished EARLY (eos) while its multi-row request is
        # still decoding stays owned: its collected tokens feed
        # _maybe_complete, so handing the slot to a new request would
        # clobber them (the stranger's tokens would surface in the
        # finished request's result, and the completion bookkeeping of
        # whichever finishes second corrupts the other's). Owner clears
        # at completion/failure — only then is the slot reusable.
        return [i for i in range(self.slots)
                if not self._active[i] and not self._reserved[i]
                and self._owner[i] is None]

    def _drain_queue(self, block: bool) -> bool:
        """Move queued requests into pending. Returns False on shutdown.
        Tier commands (session release) execute INLINE here — they are
        loop-thread state operations, not admissions, so they never
        enter the pending list or compete with requests for slots."""
        try:
            timeout = 0.2 if block else 0.0
            while True:
                req = self._q.get(block=block, timeout=timeout)
                if req is None:
                    return False
                if isinstance(req, _TierCommand):
                    self._exec_tier_command(req)
                else:
                    self._pending.append(req)
                block = False  # only the first get may wait
        except queue.Empty:
            return True

    def _admit(self) -> None:
        """Admit pending requests. Chunked admissions advance ONE chunk
        per call, so an arriving long prompt delays in-flight decode by at
        most one chunk's latency, never the whole prefill. While a
        chunked admission is in flight, ONE short (single-shot) request
        may still slip in per call — no head-of-line blocking behind a
        long prefill when free slots exist."""
        if self._adm is not None:
            self._admission_step()
            self._admit_pending(allow_chunked=False, limit=1)
            return
        self._admit_pending(allow_chunked=True)

    def _admit_pending(self, *, allow_chunked: bool,
                       limit: "int | None" = None) -> None:
        admitted = 0
        i = 0
        while i < len(self._pending) and (limit is None
                                          or admitted < limit):
            req = self._pending[i]
            # The pow2 bucket is the admission unit: bucket rows beyond n
            # also land in free slots (they must not overwrite live rows),
            # so the fit check runs on nb BEFORE any device work.
            n, width = req.block.shape
            n_rows = req.samples if req.samples > 1 else n
            nb = min(_pow2_at_least(n_rows), self.slots)
            c = self.chunk_prefill
            # Prompt-cache probe (single-prompt requests): an exact hit
            # skips the prefill outright; a prefix hit appends only the
            # suffix — IF that suffix honors the same stall bound a
            # chunked prefill enforces and fits the cache depth.
            prompt = pkey = pentry = None
            if self.prompt_cache > 0 and n == 1:
                prompt = req.ptuple()
                if req.probe is None:
                    pkey, pentry = self._pcache_lookup(prompt, req.adapter)
                    if self._tier is not None:
                        # Tier probe BEFORE declaring a pcache miss: a
                        # host-resident chain longer than the best
                        # device-resident prefix swaps in and the
                        # lookup re-runs — the restored entry then
                        # serves this admission exactly like one that
                        # never left HBM. A failed swap-in already
                        # counted its fallback; the request just
                        # proceeds with whatever the pcache had.
                        tkey = self._tier.match(req.adapter, prompt)
                        with self._lock:
                            self._stats["tier_hits" if tkey is not None
                                        else "tier_misses"] += 1
                        if self._obs is not None:
                            self._obs.on_tier_probe(tkey is not None)
                        if (tkey is not None
                                and (pkey is None
                                     or len(tkey[1]) > len(pkey))
                                and self._tier_swap_in(tkey)):
                            if req.trace is not None:
                                req.trace.event(
                                    "tier_swap_in",
                                    {"cached_len": len(tkey[1])})
                            pkey, pentry = self._pcache_lookup(
                                prompt, req.adapter)
                    if pkey is not None and len(pkey) < len(prompt):
                        g = _pow2_at_least(len(prompt) - len(pkey))
                        if (len(pkey) + g > self.max_seq
                                or (c is not None and g > c)):
                            pkey = pentry = None  # suffix too big
                    req.probe = (pkey, pentry)
                pkey, pentry = req.probe
            chunked = c is not None and width > c and pkey is None
            if chunked and not allow_chunked:
                i += 1  # long prompts wait for the in-flight one
                continue
            free = self._free_slots()
            if len(free) < nb:
                return  # strict FIFO on capacity: big requests don't starve
            if self.paged:
                need = self._pages_needed(req, pkey)
                # Pinned prompt-cache pages are reclaimable HBM: evict
                # idle entries (LRU) until the request fits — but never
                # the entry THIS request is about to share (evicting it
                # would cost more fresh pages than it frees).
                while need > self._alloc.free and self._pcache:
                    lru = next(iter(self._pcache))
                    if pkey is not None and lru == (req.adapter, pkey):
                        if len(self._pcache) == 1:
                            break
                        self._pcache[lru] = self._pcache.pop(lru)  # MRU
                        continue
                    freed = self._pcache_evict_lru()
                    with self._lock:
                        self._stats["pcache_bytes"] -= freed
                if need > self._alloc.free:
                    return  # strict FIFO: decodes must free pages first
            self._pending.pop(i)
            admitted += 1
            tr = req.trace
            if self._obs is not None:
                wait = (time.perf_counter() - tr.t_enqueue
                        if tr is not None and tr.t_enqueue is not None
                        else 0.0)
                self._obs.on_admit(tr, wait, slots=nb)
            if pkey is not None:
                exact = len(pkey) == len(prompt)
                with self._lock:
                    self._stats["pcache_hits" if exact
                                else "pcache_prefix_hits"] += 1
                if tr is not None:
                    tr.event("pcache_hit" if exact else "pcache_prefix_hit",
                             {"cached_len": len(pkey)})
                try:
                    if self.paged:
                        self._admit_hit_paged(req, free[:nb], n_rows,
                                              prompt, pkey, pentry)
                        continue
                    if exact:
                        small, last = pentry[0], pentry[1]
                    else:
                        small, last = self._pcache_extend(
                            pentry[0], prompt, len(pkey), req.adapter)
                        self._pcache_insert(prompt, small, last,
                                            req.adapter)
                    if req.samples > 1:
                        small, last = self._broadcast_rows(small, last, nb)
                    self._activate(req, free[:nb], n_rows, small, last)
                except Exception as e:  # noqa: BLE001 — fail the one request
                    self._record_backend_failure()
                    req.error = e
                    req.signal()
                continue
            if prompt is not None:
                with self._lock:
                    self._stats["pcache_misses"] += 1
                if tr is not None:
                    tr.event("pcache_miss")
            if req.samples > 1:
                # Shared-prefix fan-out: prefill the ONE prompt row; the
                # broadcast to nb rows happens at activation/finalize.
                block, lens = req.block, req.lens
            else:
                block = np.zeros((nb, width), np.int32)
                block[:n] = req.block
                lens = np.concatenate(
                    [req.lens, np.ones((nb - n,), np.int32)])
            all_rows = free[:nb]
            if chunked:
                # Start a chunked admission: reserve the slots (and, in
                # paged mode, the page chains — a later admission must
                # not steal pages this one's finalize counts on), run
                # the first chunk, and let subsequent loop iterations
                # (with decode steps in between) carry the rest.
                chains = None
                try:
                    if self.paged:
                        chains = self._alloc_request_chains(
                            req, nb, n_rows, lens)
                    small, _ = self._prefill(
                        self.params, jnp.asarray(block[:, :c]),
                        jnp.full((block.shape[0],), c, jnp.int32),
                        self._aid_arg(block.shape[0], req.adapter))
                except Exception as e:  # noqa: BLE001
                    self._record_backend_failure()
                    self._free_chains(chains)
                    req.error = e
                    req.signal()
                    continue
                for r in all_rows:
                    self._reserved[r] = True
                self._adm = {"req": req, "cache": small, "block": block,
                             "lens": lens, "pos": c, "rows": all_rows,
                             "n": n_rows, "chains": chains}
                with self._lock:
                    self._stats["adm_chunks"] += 1
                if tr is not None:
                    tr.event("prefill_chunk", {"pos": c, "of": width})
                return
            chains = None
            handed = False
            try:
                if self.paged:
                    chains = self._alloc_request_chains(req, nb, n_rows,
                                                        lens)
                small, last = self._prefill(
                    self.params, jnp.asarray(block), jnp.asarray(lens),
                    self._aid_arg(block.shape[0], req.adapter))
                if prompt is not None and not self.paged:
                    # 1-row, pre-broadcast state; the paged engine
                    # inserts AFTER packing (zero-copy page pins).
                    self._pcache_insert(prompt, small, last, req.adapter)
                if req.samples > 1 and not self.paged:
                    small, last = self._broadcast_rows(small, last, nb)
                handed = True
                self._activate(req, all_rows, n_rows, small, last,
                               chains=chains,
                               pinsert=prompt if self.paged else None)
            except Exception as e:  # noqa: BLE001 — fail the one request
                self._record_backend_failure()
                if not handed:
                    self._free_chains(chains)
                req.error = e
                req.signal()
                continue

    def _admission_step(self) -> None:
        """One chunk of the in-flight admission (or its finalize)."""
        a = self._adm
        req, c = a["req"], self.chunk_prefill
        width = a["block"].shape[1]
        try:
            if a["pos"] < width:
                end = min(a["pos"] + c, width)
                a["cache"] = self._extend_chunk(
                    self.params, a["cache"],
                    jnp.asarray(a["block"][:, a["pos"]:end]),
                    self._aid_arg(a["block"].shape[0], req.adapter))
                a["pos"] = end
                with self._lock:
                    self._stats["adm_chunks"] += 1
                if req.trace is not None:
                    req.trace.event("prefill_chunk",
                                    {"pos": end, "of": width})
                return
            # Finalize: every row consumed the padded width (short rows
            # carry junk K/V beyond their length). Reset each row's index
            # to len-1 (free rollback: junk becomes invisible) and decode
            # the row's LAST REAL token — recomputing its K/V in place and
            # yielding the exact first-token logits; index lands on len,
            # the engine's steady-state invariant.
            lens = a["lens"]
            cache = set_cache_index(a["cache"],
                                    jnp.asarray(lens - 1, jnp.int32))
            last_toks = a["block"][np.arange(len(lens)), lens - 1]
            cache, last = self._decode_logits(
                self.params, cache, jnp.asarray(last_toks),
                self._aid_arg(len(lens), req.adapter))
            pinsert = None
            if self.prompt_cache > 0 and a["block"].shape[0] == 1:
                # a["block"] row 0 == req.block row 0 by construction
                # (both admission paths copy it verbatim), so the
                # memoized key is THE key.
                if self.paged:
                    pinsert = a["req"].ptuple()
                else:
                    self._pcache_insert(a["req"].ptuple(), cache, last,
                                        req.adapter)
            if req.samples > 1 and not self.paged:
                cache, last = self._broadcast_rows(cache, last,
                                                   len(a["rows"]))
            for r in a["rows"]:
                self._reserved[r] = False
            # Chain ownership hands to _activate here: an abort after
            # this point must not double-free what the rows now hold.
            chains, a["chains"] = a.get("chains"), None
            self._adm = None
            self._activate(req, a["rows"], a["n"], cache, last,
                           chains=chains, pinsert=pinsert)
        except Exception as e:  # noqa: BLE001 — fail the one request
            self._record_backend_failure()
            self._abort_admission(a, e)

    def _abort_admission(self, a: dict, err: Exception) -> None:
        """The one admission-abort path: release the reserved rows, null
        the in-flight record, and fail its request — in that order, so no
        exit leaves rows reserved for a request nobody is waiting on.
        Takes the record explicitly (NOT via self._adm): the finalize
        branch nulls self._adm before _activate, so an _activate failure
        must still reach the record it was admitting."""
        self._adm = None
        if self.paged:
            self._free_chains(a.get("chains"))
            a["chains"] = None
        for r in a["rows"]:
            self._reserved[r] = False
        a["req"].error = err
        a["req"].signal()

    def _activate(self, req, all_rows, n, small_cache, last_logits,
                  chains=None, pinsert=None) -> None:
        """Install an admitted small cache into the slot block and light
        up the rows (shared tail of both admission paths). Dense engines
        scatter into the monolithic cache; paged engines pack the rows
        into their preallocated page ``chains`` and, when ``pinsert``
        names a prompt, pin the packed pages into the prompt cache
        (zero-copy: full pages shared by incref, tail page copied)."""
        if self.paged:
            last_logits = self._install_paged(req, all_rows, n,
                                              small_cache, last_logits,
                                              chains, pinsert)
        else:
            self._cache = self._scatter(
                self._cache, small_cache, jnp.asarray(all_rows, np.int32))
        self._light_up(req, all_rows, n, last_logits)

    def _install_paged(self, req, all_rows, n, small_cache, last_logits,
                       chains, pinsert):
        """Pack a dense-prefilled admission cache into the rows' page
        chains. samples>1 packs the ONE prompt row and fans it out
        zero-copy: siblings share row 0's full prompt pages (incref) +
        a COW'd tail + their own fresh budget pages — no n-way prompt
        replication in HBM. Returns the (possibly fanned-out)
        first-token logits."""
        ps = self.page_size
        nb = len(all_rows)
        if req.samples > 1:
            L = int(req.lens[0])
            chain0 = chains[0]
            pm = np.zeros((1, self.n_bt), np.int32)
            pm[0, :len(chain0)] = chain0
            self._cache = self._pack_pages(self._cache, small_cache,
                                           jnp.asarray(pm))
            full = L // ps
            row_chains = [chain0]
            for j in range(1, n):
                fresh = chains[j]
                self._alloc.incref(chain0[:full])
                if L % ps:
                    self._cache = self._copy_page(self._cache,
                                                  chain0[full], fresh[0])
                row_chains.append(chain0[:full] + fresh)
            row_lens = [L] * n
        else:
            pm = np.zeros((nb, self.n_bt), np.int32)
            for j in range(n):
                pm[j, :len(chains[j])] = chains[j]
            self._cache = self._pack_pages(self._cache, small_cache,
                                           jnp.asarray(pm))
            row_chains = chains[:n]
            row_lens = [int(x) for x in req.lens]
        if pinsert is not None:
            # Pin row 0's prompt pages before its first decode write
            # lands in the tail page (device ordering follows the
            # self._cache data flow — the COW copy reads the packed,
            # pre-decode state).
            self._pcache_insert_paged(pinsert, row_chains[0],
                                      last_logits[:1], req.adapter)
        for j, r in enumerate(all_rows):
            if j < n:
                self._set_row(r, row_chains[j], row_lens[j])
            else:  # pad rows: sink-page table, dense pad index of 1
                self._set_row(r, [], 1)
        if req.samples > 1:
            last_logits = jnp.broadcast_to(
                last_logits[:1], (nb, *last_logits.shape[1:]))
        return last_logits

    def _admit_hit_paged(self, req, all_rows, n, prompt, pkey,
                         pentry) -> None:
        """Prompt-cache admission without copying the cached prompt K/V:
        every admitted row maps the entry's full pages read-only into
        its block table (incref), copies the partial tail page (the row
        WILL write into it: position L lives there), and takes fresh
        pages for the rest. An exact hit does zero device attention
        work. A prefix hit first materializes row 0 and appends the
        uncached suffix batch-wide with every OTHER row's table pointed
        at the sink page — live rows' pages can't be touched, and their
        device indices are re-injected from the host mirror at the next
        dispatch — then re-decodes the last real token for the exact
        post-prefill logits and shares row 0 into the siblings."""
        ps = self.page_size
        chain0, l0, last0 = pentry[0], pentry[1], pentry[2]
        L, B = len(prompt), req.budget
        total = self._pages_for(L, B)

        def build_row(src_chain, src_len):
            sf = src_len // ps
            fresh = self._alloc.alloc(total - sf)
            if fresh is None:  # fit-checked; defensive
                raise RuntimeError("page pool exhausted mid-admission")
            self._alloc.incref(src_chain[:sf])
            if src_len % ps:
                self._cache = self._copy_page(self._cache,
                                              src_chain[sf], fresh[0])
            return list(src_chain[:sf]) + fresh

        if l0 == L:  # exact hit: host bookkeeping + stored logits only
            row_chains = [build_row(chain0, L) for _ in range(n)]
            last = last0
        else:
            r0 = all_rows[0]
            c0 = build_row(chain0, l0)
            self._set_row(r0, c0, l0)
            bts = np.zeros((self.slots, self.n_bt), np.int32)
            bts[r0] = self._tables[r0]
            idx = self._indices.copy()
            extra = np.asarray(prompt[l0:], np.int32)
            g = _pow2_at_least(len(extra))
            chunk = np.zeros((self.slots, g), np.int32)
            chunk[r0, :len(extra)] = extra
            aids = self._hit_aids(r0, req.adapter)
            self._cache = self._paged_extend(
                self.params, self._cache, jnp.asarray(idx),
                jnp.asarray(bts), jnp.asarray(chunk), aids)
            # Roll back over the suffix pad junk and re-decode the last
            # real token in place (the dense _pcache_extend invariant).
            idx[r0] = L - 1
            toks = np.zeros((self.slots,), np.int32)
            toks[r0] = prompt[-1]
            self._cache, logits = self._paged_decode_logits(
                self.params, self._cache, jnp.asarray(idx),
                jnp.asarray(bts), jnp.asarray(toks), aids)
            last = logits[r0:r0 + 1]
            self._pcache_insert_paged(prompt, c0, last, req.adapter)
            row_chains = [c0] + [build_row(c0, L) for _ in range(1, n)]
        nb = len(all_rows)
        for j, r in enumerate(all_rows):
            if j < n:
                self._set_row(r, row_chains[j], L)
            else:
                self._set_row(r, [], 1)
        if nb > 1:
            last = jnp.broadcast_to(last[:1], (nb, *last.shape[1:]))
        self._light_up(req, all_rows, n, last)

    def _hit_aids(self, r0: int, adapter: int):
        """(slots,) adapter ids for a batch-wide hit-admission call:
        row r0 uses the request's adapter, other rows keep their live
        values (their output is discarded and their writes are sinked,
        so any valid id works)."""
        if self.n_adapters is None:
            return None
        a = self._aids.copy()
        a[r0] = adapter
        return jnp.asarray(a)

    def _light_up(self, req, all_rows, n, last_logits) -> None:
        """Shared activation tail: first-token sample + slot state."""
        rows = all_rows[:n]
        nb = len(all_rows)
        temps = np.full((nb,), req.temp, np.float32)
        topks = np.full(
            (nb,), req.top_k if req.top_k else self.vocab, np.int32)
        topps = np.full(
            (nb,), 1.0 if req.top_p is None else req.top_p, np.float32)
        self._step_counter += 1
        first = np.asarray(self._first_sample(
            last_logits, jnp.asarray(temps), jnp.asarray(topks),
            jnp.asarray(topps), self._step_counter, self._base_key))
        req.slot_rows = rows
        for j, r in enumerate(rows):
            self._active[r] = True
            self._owner[r] = req
            self._aids[r] = req.adapter
            self._last_tok[r] = int(first[j])
            self._left[r] = req.budget - 1
            self._temps[r] = req.temp
            self._topks[r] = req.top_k if req.top_k else self.vocab
            self._topps[r] = 1.0 if req.top_p is None else req.top_p
            self._eos[r] = -1 if req.eos is None else int(req.eos)
            self._collected[r] = [int(first[j])]
            if self.speculate:
                # Drafting corpus: the row's real prompt (samples>1
                # shares the one prompt row) + the first token; every
                # emitted token appends, whichever path emitted it.
                src = 0 if req.samples > 1 else j
                self._spec_hist[r] = (
                    req.block[src, :int(req.lens[src])].tolist()
                    + [int(first[j])])
                self._spec_depth[r] = self.spec_gamma
        with self._lock:
            self._stats["requests"] += 1
            self._stats["tokens"] += len(rows)  # first sampled tokens
        if self._obs is not None and req.trace is not None:
            tr = req.trace
            # TTFT from ENQUEUE (the client-visible clock: queue wait +
            # prefill), not from admission.
            t0 = tr.t_enqueue
            ttft = time.perf_counter() - t0 if t0 is not None else 0.0
            self._obs.on_first_token(tr, ttft)
        if req.stream_q is not None:
            # First token per row streams immediately — it came from the
            # prefill's own logits, before any decode dispatch, so TTFT
            # is prefill latency, not prefill + a decode block.
            req.stream_q.put({j: [int(first[j])] for j in range(len(rows))})
        # eos on the very first token / budget 1 finishes immediately.
        for r in rows:
            if (self._left[r] <= 0
                    or (self._eos[r] >= 0
                        and self._last_tok[r] == self._eos[r])):
                self._finish_row(r)
        self._maybe_complete(req)

    def _finish_row(self, r: int) -> None:
        self._active[r] = False
        # Reset the slot's sampling temp: inactive rows still ride the
        # decode batch, and one stale temp>0 would disable the all-greedy
        # lax.cond fast path in _sample_rows for every later step until
        # the slot is reused.
        self._temps[r] = 0.0
        if self.speculate:
            self._spec_hist[r] = []  # corpus dies with the row
        if self.paged:
            # Session-end insert BEFORE the release below: the chain's
            # pages must be pinned while the row still holds its refs,
            # or the free list could hand them out in between.
            req = self._owner[r]
            if (req is not None and req.session is not None
                    and req.samples == 1 and req.block.shape[0] == 1
                    and self.prompt_cache > 0
                    and self._collected[r]):
                self._session_insert(req, r)
            # Free the row's pages NOW, not at request completion: the
            # zeroed table row sinks the slot's continued decode writes,
            # and shared prompt pages just drop a refcount — so a long
            # sibling can't hold a finished row's HBM hostage.
            self._release_slot_pages(r)

    def _fail_request(self, req: "_Request", err: Exception) -> None:
        for r in req.slot_rows:
            self._active[r] = False
            self._temps[r] = 0.0  # keep the all-greedy fast path alive
            self._owner[r] = None
            self._collected[r] = []
            if self.paged:
                self._release_slot_pages(r)
        req.error = err
        req.signal()

    def _expire_deadlines(self) -> None:
        """Free resources of requests whose client stopped waiting."""
        now = time.time()
        n_expired = 0
        expired = [r for r in self._pending if now > r.deadline]
        for req in expired:
            self._pending.remove(req)
            req.error = TimeoutError("expired while queued")
            req.signal()
            n_expired += 1
        # The in-flight chunked admission too: its client may have given
        # up mid-prefill, and without this check the remaining chunks (and
        # the whole decode budget) would still run for nobody.
        if self._adm is not None and now > self._adm["req"].deadline:
            self._abort_admission(self._adm,
                                  TimeoutError("expired during admission"))
            n_expired += 1
        for req in {self._owner[r] for r in range(self.slots)
                    if self._owner[r] is not None}:
            if now > req.deadline:
                self._fail_request(
                    req, TimeoutError("expired while decoding"))
                n_expired += 1
        if n_expired:
            with self._lock:
                self._stats["deadline_expired"] += n_expired

    def _maybe_complete(self, req: "_Request") -> None:
        if any(self._active[r] for r in req.slot_rows):
            return
        pad_to = req.budget
        if self._obs is not None and req.trace is not None:
            tr = req.trace
            now = time.perf_counter()
            e2e = now - tr.t_enqueue if tr.t_enqueue is not None else 0.0
            # Mean time per output token after the first, over the
            # longest row (rows decode in lockstep, so the longest row's
            # clock is the request's decode clock). Computed BEFORE the
            # loop below clears the collected lists.
            ntok = min(max((len(self._collected[r])
                            for r in req.slot_rows), default=0), pad_to)
            tpot = ((now - tr.t_first) / (ntok - 1)
                    if tr.t_first is not None and ntok > 1 else None)
            self._obs.on_complete(tr, e2e, tpot)
        out = []
        for r in req.slot_rows:
            toks = self._collected[r][:pad_to]
            toks += [toks[-1]] * (pad_to - len(toks))  # eos-extend
            out.append(toks)
            self._owner[r] = None
            self._collected[r] = []
            if self.paged:
                self._release_slot_pages(r)  # no-op after _finish_row
        req.tokens = out
        req.signal()

    def _record_backend_failure(self) -> None:
        if self.breaker is not None:
            self.breaker.record_failure()

    def _crash_reset(self, err: Exception) -> None:
        """Crash-only containment after an unexpected dispatch failure
        (or a dead loop thread being revived): fail everything holding
        device state CLEANLY, then rebuild the host-side cache
        bookkeeping to a verified-empty baseline. The KV pool arrays
        themselves need no scrubbing — rows/pages are fully overwritten
        at admission, and junk beyond a row's index is invisible to the
        position mask — but the prompt cache and page chains may
        reference state the failed dispatch left unknown, so both are
        dropped wholesale. Queued/pending requests survive: they hold no
        device state and the resumed loop serves them."""
        for req in {o for o in self._owner if o is not None}:
            req.error = err
            req.signal()
        if self._adm is not None:
            a, self._adm = self._adm, None
            a["req"].error = err
            a["req"].signal()
        self._active[:] = False
        self._reserved[:] = False
        self._owner = [None] * self.slots
        self._collected = [[] for _ in range(self.slots)]
        self._temps[:] = 0.0  # keep the all-greedy fast path alive
        if self.speculate:
            self._spec_hist = [[] for _ in range(self.slots)]
            self._spec_depth[:] = self.spec_gamma
        # The pcache drops WHOLESALE, no tier swap-out: the failed
        # dispatch left device state untrusted, and gathering unknown
        # bytes to host would let corruption outlive the reset. Chains
        # already on the host tier are fine (they reference no device
        # pages) — sessions keep only the keys the tier still holds.
        self._pcache.clear()
        self._sessions = (
            {sid: k for sid, k in self._sessions.items()
             if self._tier is not None and self._tier.contains(k)})
        with self._lock:
            self._stats["pcache_bytes"] = 0
            self._stats["loop_crashes"] += 1
        if self.paged:
            self._alloc = _PageAllocator(self.num_pages)
            self._pinned = {}
            self._chains = [[] for _ in range(self.slots)]
            self._tables[:] = 0
            self._indices[:] = 0
            if self._alloc.free != self._alloc.total:  # verified-empty
                raise RuntimeError(
                    f"allocator reset left {self._alloc.total - self._alloc.free} "
                    f"pages unaccounted")

    def _watchdog_loop(self) -> None:
        """Detects (a) a dead loop thread — revives it after a crash
        reset — and (b) a stalled loop (a wedged device dispatch: the
        heartbeat, stamped once per iteration, goes stale; a HEALTHY
        idle loop wakes every 0.2 s via _drain_queue's timeout). A stall
        fails every blocked client with a retryable EngineStalled
        instead of letting them hang to their full timeout, and trips
        the breaker so /healthz pulls the pod from rotation."""
        poll = max(0.01, min(self.watchdog_s / 4.0, 1.0))
        while not self._wd_stop.wait(poll):
            if self._closed:
                return
            if not self._thread.is_alive():
                self._revive_loop()
                continue
            if time.monotonic() - self._heartbeat < self.watchdog_s:
                continue
            with self._lock:
                waiters = list(self._waiters)
            if not waiters:
                continue  # nobody is blocked on the stalled loop
            with self._lock:
                self._stats["watchdog_trips"] += 1
            if self.breaker is not None:
                self.breaker.trip_open()
            err = EngineStalled(
                f"engine loop made no dispatch progress for "
                f">= {self.watchdog_s:.1f}s; request failed cleanly, retry")
            for req in waiters:
                # deadline 0 makes the loop reap the rows/queue entry via
                # _expire_deadlines whenever it resumes; the waiter is
                # released NOW.
                req.deadline = 0.0
                req.error = err
                req.signal()
            # A trip consumes the stale window: the next trip requires
            # another full watchdog_s of no progress. Without this, a
            # request arriving while the loop is still wedged is failed on
            # the very next poll tick instead of getting its own grace
            # period to see the loop recover.
            self._heartbeat = time.monotonic()

    def _revive_loop(self) -> None:
        """The loop thread died (an exception escaped _loop — e.g. an
        injected engine_loop fault). Crash-reset its state and start a
        fresh thread; this runs on the watchdog thread, which is safe
        only BECAUSE the loop thread is dead."""
        if self._closed:
            return
        exc, self._loop_exc = self._loop_exc, None
        err = EngineStalled(
            f"engine loop thread died ({exc!r}); state reset, retry")
        self._record_backend_failure()
        self._crash_reset(err)
        with self._lock:
            self._stats["loop_restarts"] += 1
        self._thread = threading.Thread(target=self._loop_main, daemon=True,
                                        name="generate-engine")
        self._thread.start()

    def _spec_iteration(self, aids, t0: float) -> bool:
        """One speculative decode iteration: draft per-row proposals,
        verify them in ONE batch-wide extend, emit each row's accepted
        prefix + the target's correction token. Returns True when it
        handled the dispatch (all bookkeeping done, loop continues);
        False falls through to the plain decode path — taken when no
        row proposes anything, any row samples (verify is argmax-only),
        any row sits too close to the cache end for the static verify
        width, or the verify dispatch itself fails (chaos ``spec_verify``
        or a real backend error: that batch decodes plainly instead of
        wedging the loop).

        Exactness: the verify extend over ``[x0, d1..d_gamma]`` is
        computationally identical to the plain path decoding x0, d1,
        ... in sequence — accepted positions get exactly the K/V the
        plain path would have written, and the host index advances by
        exactly the tokens consumed (m accepted drafts + x0), so the
        correction token's K/V lands on the NEXT dispatch as that
        chunk's position 0, same as plain decode. Rejected-draft writes
        sit past the new index: invisible to the position mask and
        overwritten before the index ever reaches them."""
        W = self.spec_gamma + 1
        if (self._temps > 0.0).any():
            return False
        # Static verify width vs cache end: a chunk always writes W
        # positions, and a row within W of max_seq would clamp those
        # writes back INTO its own last page (the plain path's harmless
        # finished-row clamp is harmful here: extend's attention reads
        # the corruption in the same call). Rare and transient — such
        # rows are at most spec_gamma tokens from finishing.
        if bool((self._indices[self._active] + W > self.max_seq).any()):
            return False
        t_draft = time.perf_counter()
        props: "list[list[int]]" = [[] for _ in range(self.slots)]
        any_prop = False
        for r in range(self.slots):
            if not self._active[r]:
                continue
            depth = int(min(self._spec_depth[r], self._left[r] - 1))
            if depth <= 0:
                continue
            p = self._drafter.propose(self._spec_hist[r], depth)
            if p:
                props[r] = p
                any_prop = True
        if not any_prop:
            return False
        draft_s = time.perf_counter() - t_draft
        chunk = np.zeros((self.slots, W), np.int32)
        chunk[:, 0] = self._last_tok
        for r in range(self.slots):
            if props[r]:
                chunk[r, 1:1 + len(props[r])] = props[r]
        t_verify = time.perf_counter()
        try:
            if self._chaos is not None:
                self._chaos.fire("spec_verify")
            self._cache, tgt = self._spec_verify(
                self.params, self._cache, jnp.asarray(self._indices),
                jnp.asarray(self._tables), jnp.asarray(chunk), aids)
            tgt = np.asarray(tgt)
        except Exception:  # noqa: BLE001 — plain decode serves this batch
            with self._lock:
                self._stats["spec_fallbacks"] += 1
            return False
        verify_s = time.perf_counter() - t_verify
        if self.breaker is not None:
            self.breaker.record_success()
        dt = time.perf_counter() - t0
        n_active = int(self._active.sum())
        done_reqs = set()
        deltas: "dict[_Request, dict[int, list[int]]]" = {}
        consumed = proposed = accepted = 0
        for r in range(self.slots):
            if not self._active[r]:
                continue
            plen = len(props[r])
            m = 0
            while m < plen and props[r][m] == int(tgt[r, m]):
                m += 1
            proposed += plen
            accepted += m
            if plen:
                # Per-slot depth adaptation: full accept earns a deeper
                # next proposal, full reject a shallower one. Depth only
                # changes how much is PROPOSED — never what is emitted —
                # so exactness is adaptation-blind.
                if m == plen:
                    self._spec_depth[r] = min(self._spec_depth[r] + 1,
                                              self.spec_gamma)
                elif m == 0:
                    self._spec_depth[r] = max(1, self._spec_depth[r] - 1)
            emitted = props[r][:m] + [int(tgt[r, m])]
            owner = self._owner[r]
            row_consumed = 0
            for tok in emitted:
                self._last_tok[r] = tok
                self._collected[r].append(tok)
                self._spec_hist[r].append(tok)
                self._left[r] -= 1
                row_consumed += 1
                if owner is not None and owner.stream_q is not None:
                    deltas.setdefault(owner, {}).setdefault(
                        owner.slot_rows.index(r), []).append(tok)
                if self._left[r] <= 0 or (self._eos[r] >= 0
                                          and tok == self._eos[r]):
                    self._finish_row(r)
                    done_reqs.add(owner)
                    break  # tokens past eos/budget are discarded
            consumed += row_consumed
            # Cache truth after this dispatch: positions index+1 ..
            # index+row_consumed hold x0 + the accepted drafts' K/V
            # (an eos-truncated row advances less, but it just finished
            # — its next use rewrites index and table wholesale).
            self._indices[r] += row_consumed
        for req, d in deltas.items():
            req.stream_q.put(d)
        with self._lock:
            # One extend over the batch ~= one device decode step of
            # work, so "steps" (the per-step unit avg_active_slots
            # divides by) advances by 1 while "tokens" advances by
            # everything emitted — tokens/dispatches IS the speculation
            # win, spec_accepted/spec_proposed the acceptance rate.
            self._stats["steps"] += 1
            self._stats["dispatches"] += 1
            self._stats["tokens"] += consumed
            self._stats["busy_s"] += dt
            self._stats["slot_occupancy_sum"] += n_active
            self._stats["peak_active_slots"] = max(
                self._stats["peak_active_slots"], n_active)
            self._stats["spec_dispatches"] += 1
            self._stats["spec_proposed"] += proposed
            self._stats["spec_accepted"] += accepted
            self._stats["spec_emitted"] += consumed
        if self._obs is not None:
            self._obs.on_dispatch(n_active, len(self._pending),
                                  self._alloc.free,
                                  self._alloc.total - self._alloc.free)
            self._obs.on_decode_dispatch(dt, self._decode_mfu(consumed, dt))
            self._obs.on_spec_dispatch(proposed, accepted, consumed,
                                       draft_s, verify_s)
            if self._obs.enabled:
                seen = set()
                attrs = {"spec": True, "proposed": proposed,
                         "accepted": accepted, "active": n_active,
                         "dt_ms": round(dt * 1e3, 3)}
                for r in range(self.slots):
                    o = self._owner[r]
                    if o is None or o.trace is None or id(o) in seen:
                        continue
                    seen.add(id(o))
                    o.trace.event("decode", attrs)
        for req in done_reqs:
            self._maybe_complete(req)
        return True

    def _loop_main(self) -> None:
        try:
            self._loop()
        except Exception as e:  # noqa: BLE001 — crash-only: watchdog revives
            self._loop_exc = e

    def _loop(self) -> None:
        while True:
            self._heartbeat = time.monotonic()
            if self._chaos is not None:
                # Outside the dispatch try on purpose: a raised fault
                # here kills the loop thread (the watchdog-revival path).
                self._chaos.fire("engine_loop")
            any_active = bool(self._active.any())
            if not self._drain_queue(block=not any_active
                                     and not self._pending
                                     and self._adm is None):
                break  # shutdown sentinel
            self._expire_deadlines()
            self._admit()
            if (self.paged and self._tier is not None
                    and self.tier_watermark > 0):
                self._tier_pressure()
            if not self._active.any():
                continue
            t0 = time.perf_counter()
            self._step_counter += 1
            k_tok = self.decode_block
            aids = (jnp.asarray(self._aids)
                    if self.n_adapters is not None else None)
            if self.speculate and self._spec_iteration(aids, t0):
                continue
            try:
                if self._chaos is not None:
                    self._chaos.fire("decode_dispatch")
                targs = (jnp.asarray(self._last_tok),
                         jnp.asarray(self._temps),
                         jnp.asarray(self._topks),
                         jnp.asarray(self._topps),
                         self._step_counter, self._base_key)
                if self.paged:
                    pargs = (jnp.asarray(self._indices),
                             jnp.asarray(self._tables))
                    if k_tok == 1:
                        self._cache, nxt = self._paged_decode_step(
                            self.params, self._cache, *pargs, *targs,
                            aids)
                        block = np.asarray(nxt)[None]      # (1, B)
                    else:
                        self._cache, nxt = self._paged_decode_block_step(
                            self.params, self._cache, *pargs, *targs,
                            k_tok, aids)
                        block = np.asarray(nxt)            # (K, B)
                    # The dispatch advanced EVERY row's device index by
                    # k_tok; the host mirror (the injected truth) must
                    # track it, active or not — exactly like the dense
                    # cache's own index leaves.
                    self._indices += k_tok
                elif k_tok == 1:
                    self._cache, nxt = self._decode_step(
                        self.params, self._cache, *targs, aids)
                    block = np.asarray(nxt)[None]          # (1, B)
                else:
                    self._cache, nxt = self._decode_block_step(
                        self.params, self._cache, *targs, k_tok, aids)
                    block = np.asarray(nxt)                # (K, B)
            except Exception as e:  # noqa: BLE001 — crash-only reset
                self._record_backend_failure()
                self._crash_reset(e)
                continue
            if self.breaker is not None:
                self.breaker.record_success()
            dt = time.perf_counter() - t0
            n_active = int(self._active.sum())
            done_reqs = set()
            consumed = 0
            deltas: "dict[_Request, dict[int, list[int]]]" = {}
            for j in range(block.shape[0]):
                for r in range(self.slots):
                    if not self._active[r]:
                        continue  # finished mid-block: surplus discarded
                    tok = int(block[j, r])
                    self._last_tok[r] = tok
                    self._collected[r].append(tok)
                    if self.speculate:
                        self._spec_hist[r].append(tok)
                    self._left[r] -= 1
                    consumed += 1
                    owner = self._owner[r]
                    if owner is not None and owner.stream_q is not None:
                        deltas.setdefault(owner, {}).setdefault(
                            owner.slot_rows.index(r), []).append(tok)
                    if self._left[r] <= 0 or (self._eos[r] >= 0
                                              and tok == self._eos[r]):
                        self._finish_row(r)
                        done_reqs.add(owner)
            # Deltas flush BEFORE completion: the terminal marker from
            # signal() must be the stream's last item.
            for req, d in deltas.items():
                req.stream_q.put(d)
            with self._lock:
                # "steps" keeps its per-token meaning (device decode
                # steps) so the exported counter's unit survives the
                # k>1 default; "dispatches" counts device round-trips —
                # steps/dispatches is the realized block amortization.
                self._stats["steps"] += block.shape[0]
                self._stats["dispatches"] += 1
                self._stats["tokens"] += consumed
                self._stats["busy_s"] += dt
                self._stats["slot_occupancy_sum"] += (n_active
                                                      * block.shape[0])
                self._stats["peak_active_slots"] = max(
                    self._stats["peak_active_slots"], n_active)
            if self._obs is not None:
                self._obs.on_dispatch(
                    n_active, len(self._pending),
                    self._alloc.free if self.paged else None,
                    (self._alloc.total - self._alloc.free)
                    if self.paged else None)
                self._obs.on_decode_dispatch(
                    dt, self._decode_mfu(consumed, dt))
                if self._obs.enabled:
                    # One "decode" event per request per dispatch (not
                    # per token): slots is small, so this scan is noise
                    # next to the device round-trip above.
                    seen = set()
                    attrs = {"k": block.shape[0], "active": n_active,
                             "dt_ms": round(dt * 1e3, 3)}
                    for r in range(self.slots):
                        o = self._owner[r]
                        if (o is None or o.trace is None
                                or id(o) in seen):
                            continue
                        seen.add(id(o))
                        o.trace.event("decode", attrs)
            for req in done_reqs:
                self._maybe_complete(req)
        # Shutdown: fail anything still waiting — INCLUDING requests a
        # racing submit() enqueued behind the sentinel (they would
        # otherwise block their caller for the full submit timeout).
        err = RuntimeError("engine closed")
        try:
            while True:
                req = self._q.get(block=False)
                if req is not None:
                    self._pending.append(req)
        except queue.Empty:
            pass
        if self._adm is not None:
            self._pending.append(self._adm["req"])
            self._adm = None
        for req in self._pending:
            req.error = err
            req.signal()
        for req in {o for o in self._owner if o is not None}:
            req.error = err
            req.signal()
