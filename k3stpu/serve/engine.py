"""Continuous batching for LM generation — slot-based decode scheduling.

``generate_tokens`` runs whole requests back-to-back: a 256-token
generation holds the chip while later requests queue, and a batch-1
request decodes alone at batch-1 arithmetic intensity. This engine is the
TPU-native fix (the serving pattern vLLM/Orca made standard, built here on
XLA-static shapes):

- ONE decode program, compiled once, over a fixed block of ``slots`` cache
  rows. Every step advances all active slots together; per-row cache
  indices (models/transformer.py) let rows sit at different depths.
- Requests JOIN mid-flight: a free slot gets the new request's prefilled
  cache rows scattered in between decode steps; finished slots free
  immediately. No request waits for another to finish, and decode batch
  density — the thing MXU throughput scales with — stays high under load.
- Everything device-side is shape-static: prefill widths and admitted-row
  counts come from small power-of-two bucket sets, so steady state runs a
  handful of compiled programs, never a recompile.
- Per-slot sampling params travel as traced (B,) arrays (temperature,
  top-k, eos), so heterogeneous requests share the one decode program.

The reference has no serving scheduler at all (its workload is a stock
binary behind a Service, reference jellyfin.yaml:1-43); this is the
match-or-beat half of the serving story.
"""

from __future__ import annotations

import functools
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from k3stpu.models.generate import init_cache, set_cache_index
from k3stpu.serve.programs import (
    decode_core,
    extend_core,
    prefill_core,
    prompt_width_bucket,
)

_NEG_INF = -1e30


class EngineOverloaded(RuntimeError):
    """Raised by submit paths when max_pending requests are already in
    flight — the backpressure signal the HTTP layer turns into a 503
    (shed load at the door; queueing unboundedly just converts overload
    into client timeouts plus held memory)."""


def _pow2_at_least(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def _sample_rows(logits, temps, topks, topps, key):
    """Per-row sampling over (B, V) logits: temperature <= 0 is greedy;
    top-k cuts below each row's own k-th value (k == V disables); top-p
    keeps each row's smallest nucleus reaching mass p (1.0 disables).

    The all-greedy batch — the dominant serving case, and every decode
    step of the exactness-pinned capture runs — skips the sampling
    machinery entirely via ``lax.cond``: the mixed path pays two full
    (B, V) sorts (top-k kth-value + top-p nucleus) per step, pure
    VPU/HBM waste when no row will use the result."""
    from k3stpu.models.generate import top_p_mask

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def mixed(_):
        v = logits.shape[-1]
        scaled = logits / jnp.clip(temps, 1e-6, None)[:, None]
        srt = jnp.sort(scaled, axis=-1)
        kth = jnp.take_along_axis(
            srt, (v - jnp.clip(topks, 1, v))[:, None], axis=-1)
        cut = jnp.where(scaled < kth, _NEG_INF, scaled)
        cut = top_p_mask(cut, topps)
        sampled = jax.random.categorical(key, cut,
                                         axis=-1).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    return jax.lax.cond(jnp.all(temps <= 0.0), lambda _: greedy, mixed,
                        None)


class _Request:
    __slots__ = ("block", "lens", "budget", "temp", "top_k", "top_p",
                 "eos", "event", "tokens", "error", "slot_rows", "samples",
                 "deadline", "stream_q", "_ptuple", "probe", "adapter")

    def __init__(self, block, lens, budget, temp, top_k, eos, samples=1,
                 top_p=None, adapter=0):
        self.block = block          # (n, P) int32, right-padded
        self.lens = lens            # (n,) true lengths
        self.budget = budget        # max new tokens (shared by the rows)
        self.temp = temp
        self.top_k = top_k
        self.top_p = top_p          # float | None (None == 1.0, no cut)
        self.eos = eos              # int | None
        self.samples = samples      # >1: one prompt, n sampled rows
        self.adapter = adapter      # multi-LoRA slot (0 = base)
        self.event = threading.Event()
        self.tokens: "list[list[int]] | None" = None
        self.error: "Exception | None" = None
        self.slot_rows: "list[int]" = []
        self.deadline: float = float("inf")  # set by _enqueue_and_wait
        # submit_stream() installs a queue here; the loop thread pushes
        # per-block token deltas into it and signal() pushes the terminal
        # None. Non-streaming requests leave it None (zero overhead).
        self.stream_q: "queue.SimpleQueue | None" = None
        self._ptuple: "tuple | None" = None  # memoized prompt key
        # Memoized prompt-cache probe result (pkey, pentry) — the probe
        # re-runs every loop iteration while the request waits for free
        # slots, and re-scanning the cache each time is pure engine-
        # thread waste. A stale entry stays CORRECT (immutable arrays);
        # the only cost is missing a better prefix inserted meanwhile.
        self.probe: "tuple | None" = None

    def ptuple(self) -> tuple:
        """The single-prompt cache key, computed once — the admission
        probe re-runs while a request waits for free slots, and an
        O(prompt) conversion per loop iteration on the engine thread
        is waste (the block is immutable after packing)."""
        if self._ptuple is None:
            self._ptuple = tuple(
                int(t) for t in self.block[0, :int(self.lens[0])])
        return self._ptuple

    def signal(self) -> None:
        """Wake the submitter on EVERY terminal path (tokens ready, error,
        expiry, shutdown): terminal stream marker first, THEN the event —
        a streaming consumer must never wait on a queue nobody will feed
        again."""
        if self.stream_q is not None:
            self.stream_q.put(None)
        self.event.set()


class GenerateEngine:
    """Owns a ``slots``-row KV cache and a single decode loop thread.

    ``submit()`` blocks the calling (HTTP handler) thread until its
    request's rows finish; the loop thread interleaves every live request
    into one decode batch. ``close()`` drains and stops the thread.
    """

    def __init__(self, model, params, *, slots: int = 8,
                 seed: int = 0, chunk_prefill: "int | None" = None,
                 decode_block: int = 1, prompt_cache: int = 0,
                 mesh=None, max_pending: "int | None" = None):
        """``chunk_prefill``: admit long prompts in chunks of this many
        tokens, one chunk per loop iteration — bounds how long a decode
        step can be delayed by an arriving prompt to one chunk's latency
        instead of the whole prompt's. None = single-shot admission.

        ``decode_block``: decode this many tokens per device dispatch
        (an inner ``lax.scan``), host-side eos/budget/deadline checks in
        between blocks. Through a relayed backend each dispatch costs
        ~8 ms regardless of work, capping a per-token loop at ~125
        steps/s; a K-token block amortizes that floor K-fold. Trade-off:
        a new request joins on a block boundary (K-token granularity),
        and a row that hits eos mid-block rides out the rest of the
        block with its surplus tokens discarded host-side.

        ``prompt_cache``: keep up to this many prefilled single-prompt
        KV rows (LRU) keyed by the exact prompt tokens. A repeat prompt
        skips its prefill entirely; a prompt that EXTENDS a cached one
        restores the row and appends only the new tokens (the chat /
        shared-system-prompt pattern — prefill cost drops from O(whole
        prompt) to O(new suffix)). Cost: one full-depth cache row of
        HBM per entry (``stats()['pcache_bytes']``). Outputs are
        bit-identical to the uncached path: the restored row IS the
        prefilled row (jax arrays are immutable, so a cached row can't
        be corrupted by the decodes of the slot it was scattered into),
        and the suffix-append reuses the chunked-admission finalize
        invariant (junk K/V beyond a row's index is invisible to the
        position mask and gets overwritten slot-by-slot). 0 disables.

        ``mesh``: tensor-parallel serving over a jax Mesh with a
        'model' axis (parallel/mesh.make_mesh's convention — required).
        The params arrive sharded over that axis
        (parallel/sharding.py); the KV cache must live on the SAME
        devices or jit refuses the mixed placement, so it goes up
        sharded on its kv-head axis where divisible (attention splits
        by head under TP) and replicated otherwise. Host-side numpy
        inputs stay uncommitted — jit places them. None =
        single-device (programs unchanged)."""
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if mesh is not None and "model" not in mesh.shape:
            raise ValueError(
                f"engine mesh needs a 'model' axis, got {mesh.shape}")
        if chunk_prefill is not None and chunk_prefill < 1:
            raise ValueError(f"chunk_prefill must be >= 1, got "
                             f"{chunk_prefill}")
        if decode_block < 1:
            raise ValueError(f"decode_block must be >= 1, got "
                             f"{decode_block}")
        if prompt_cache < 0:
            raise ValueError(f"prompt_cache must be >= 0, got "
                             f"{prompt_cache}")
        self.model = model
        self.params = params
        self.slots = slots
        self.chunk_prefill = chunk_prefill
        self.decode_block = decode_block
        cfg = getattr(model.config, "base", model.config)
        self.max_seq = cfg.max_seq_len
        self.vocab = cfg.vocab_size
        # Multi-LoRA serving (models/lora.py MultiLoraDense): per-slot
        # adapter ids travel as a traced (B,) array, so requests on
        # DIFFERENT fine-tunes share the one decode program/batch. None
        # when the model has no adapter stacks — every core is then
        # called exactly as before (no recompile, no behavior change).
        self.n_adapters = getattr(cfg, "multi_lora", None)

        self._cache = init_cache(model, slots)
        self.mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def _cache_sharding(x):
                # (B, S, H, D) K/V and (B, S, H) scale leaves shard on
                # the head axis; (B,) index and anything indivisible
                # replicate.
                if x.ndim >= 3 and x.shape[2] % mesh.shape["model"] == 0:
                    return NamedSharding(mesh, P(None, None, "model"))
                return NamedSharding(mesh, P())

            self._cache = jax.tree.map(
                lambda x: jax.device_put(x, _cache_sharding(x)),
                self._cache)
        self._base_key = jax.random.key(seed)
        self._step_counter = 0

        # Host-side slot state (numpy: mutated only by the loop thread).
        self._active = np.zeros((slots,), bool)
        self._reserved = np.zeros((slots,), bool)  # chunked admission holds
        self._last_tok = np.zeros((slots,), np.int32)
        self._left = np.zeros((slots,), np.int64)
        self._temps = np.zeros((slots,), np.float32)
        self._topks = np.full((slots,), 1, np.int32)
        self._topps = np.ones((slots,), np.float32)
        self._eos = np.full((slots,), -1, np.int32)
        self._aids = np.zeros((slots,), np.int32)  # multi-LoRA slots
        self._owner: "list[_Request | None]" = [None] * slots
        self._collected: "list[list[int]]" = [[] for _ in range(slots)]

        # Admission bound: requests in flight (queued, admitting, or
        # decoding — counted from enqueue until the consumer returns).
        self.max_pending = max_pending
        self._inflight = 0  # guarded by _lock
        self._q: "queue.SimpleQueue[_Request | None]" = queue.SimpleQueue()
        self._pending: "list[_Request]" = []
        self._adm: "dict | None" = None  # in-flight chunked admission
        self._closed = False
        self._lock = threading.Lock()
        self._stats = {"tokens": 0, "steps": 0, "dispatches": 0,
                       "busy_s": 0.0, "requests": 0,
                       "slot_occupancy_sum": 0.0, "adm_chunks": 0,
                       "pcache_hits": 0, "pcache_prefix_hits": 0,
                       "pcache_misses": 0, "pcache_bytes": 0,
                       "rejected": 0}
        # Prompt cache: tuple(prompt tokens) -> (cache_1row, last_1row),
        # insertion-ordered dict as LRU (loop thread only).
        self.prompt_cache = prompt_cache
        self._pcache: "dict[tuple, tuple]" = {}

        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="generate-engine")
        self._thread.start()

    # --- jitted device programs (compiled once per static bucket) -------

    # params travel as jit ARGUMENTS (donated weights would bake into the
    # compiled program as constants otherwise — double the HBM). The
    # cache-model programs themselves are the shared cores in
    # serve/programs.py (one definition for engine + speculative).

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_step(self, params, cache, toks, temps, topks, topps,
                     step, base_key, aids=None):
        cache, logits = decode_core(self.model, params, cache, toks,
                                    adapter_ids=aids)
        key = jax.random.fold_in(base_key, step)
        return cache, _sample_rows(logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 9))
    def _decode_block_step(self, params, cache, toks, temps, topks,
                           topps, step, base_key, k_tokens: int,
                           aids=None):
        """K decode steps in ONE dispatch: ``lax.scan`` over the
        single-token core, sampling on-device each step. Returns the
        (K, B) token block; greedy rows are exactly K steps of argmax,
        so engine output stays pinned to ``generate()`` token for
        token. Rows that finish mid-block keep decoding (static shapes;
        the host discards their surplus) — their cache writes clamp at
        the row's last slot and the slot's next reuse scatters a fresh
        prefill over everything, index included."""
        block_key = jax.random.fold_in(base_key, step)

        def body(carry, i):
            cache, tok = carry
            cache, logits = decode_core(self.model, params, cache, tok,
                                        adapter_ids=aids)
            key = jax.random.fold_in(block_key, i)
            nxt = _sample_rows(logits, temps, topks, topps, key)
            return (cache, nxt), nxt

        (cache, _), out = jax.lax.scan(
            body, (cache, toks), jnp.arange(k_tokens))
        return cache, out

    @functools.partial(jax.jit, static_argnums=(0,))
    def _prefill(self, params, block, lens, aids=None):
        return prefill_core(self.model, params, block, lens,
                            adapter_ids=aids)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _scatter(self, big, small, slot_ids):
        return jax.tree.map(lambda b, s: b.at[slot_ids].set(s), big, small)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _extend_chunk(self, params, cache, chunk, aids=None):
        return extend_core(self.model, params, cache, chunk,
                           adapter_ids=aids)[0]

    @functools.partial(jax.jit, static_argnums=(0,))
    def _decode_logits(self, params, cache, toks, aids=None):
        return decode_core(self.model, params, cache, toks,
                           adapter_ids=aids)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _first_sample(self, last_logits, temps, topks, topps, step,
                      base_key):
        key = jax.random.fold_in(base_key, step)
        return _sample_rows(last_logits, temps, topks, topps, key)

    @functools.partial(jax.jit, static_argnums=(0, 3))
    def _broadcast_rows(self, cache, last, n: int):
        """Row 0 of a 1-row admission cache replicated to n rows — the
        shared-prefix fan-out (one prefill, n sampled continuations)."""
        rep = jax.tree.map(
            lambda x: jnp.broadcast_to(x[:1], (n, *x.shape[1:])), cache)
        return rep, jnp.broadcast_to(last[:1], (n, *last.shape[1:]))

    # --- prompt cache (loop thread only; entries are immutable jax
    #     arrays, so a cached row survives the decodes of whatever slot
    #     its copy was scattered into) ------------------------------------

    def _pcache_lookup(self, prompt: tuple, adapter: int = 0):
        """Longest cached entry equal to ``prompt`` or a proper prefix of
        it, UNDER THE SAME ADAPTER (a row prefilled through adapter i's
        deltas is a different computation — cross-adapter reuse would be
        silently wrong); a hit refreshes its LRU position. Returns the
        PROMPT part of the key."""
        best = None
        for aid, key in self._pcache:
            if (aid == adapter and len(key) <= len(prompt)
                    and prompt[:len(key)] == key
                    and (best is None or len(key) > len(best))):
                best = key
        if best is None:
            return None, None
        entry = self._pcache.pop((adapter, best))  # re-insert at MRU
        self._pcache[(adapter, best)] = entry
        return best, entry

    def _pcache_insert(self, prompt: tuple, cache1, last1,
                       adapter: int = 0) -> None:
        if self.prompt_cache <= 0:
            return
        old = self._pcache.pop((adapter, prompt), None)
        nbytes = sum(x.nbytes for x in jax.tree.leaves((cache1, last1)))
        self._pcache[(adapter, prompt)] = (cache1, last1, nbytes)
        delta = nbytes - (old[2] if old else 0)
        while len(self._pcache) > self.prompt_cache:
            evicted = self._pcache.pop(next(iter(self._pcache)))
            delta -= evicted[2]
        with self._lock:
            self._stats["pcache_bytes"] = (
                self._stats.get("pcache_bytes", 0) + delta)

    def _pcache_extend(self, cache1, prompt: tuple, p0: int,
                       adapter: int = 0):
        """Append ``prompt[p0:]`` to a restored 1-row cache (row index sits
        at p0). Returns (cache, last_logits) in EXACTLY the post-prefill
        state: the suffix pads to a pow2 chunk, the index rolls back to
        len-1 (pad junk becomes invisible to the position mask, the
        chunked-admission finalize invariant) and the last real token is
        re-decoded in place for the exact first-token logits."""
        extra = np.asarray(prompt[p0:], np.int32)[None]
        g = _pow2_at_least(extra.shape[1])
        pad = np.zeros((1, g), np.int32)
        pad[:, :extra.shape[1]] = extra
        aids = self._aid_arg(1, adapter)
        cache = self._extend_chunk(self.params, cache1, jnp.asarray(pad),
                                   aids)
        cache = set_cache_index(
            cache, jnp.asarray([len(prompt) - 1], jnp.int32))
        return self._decode_logits(
            self.params, cache, jnp.asarray([prompt[-1]], jnp.int32), aids)

    def _aid_arg(self, n: int, adapter: int):
        """(n,)-row adapter-id array for a single request's device call —
        None when the model carries no adapter stacks (exact pre-multi-
        LoRA program signatures)."""
        if self.n_adapters is None:
            return None
        return jnp.full((n,), adapter, jnp.int32)

    # --- client API -----------------------------------------------------

    def _packed_request(self, prompts, max_new_tokens, temperature, top_k,
                        eos_id, samples=1, top_p=None,
                        adapter_id=0) -> "_Request":
        """Shared validation + packing for both entry points: right-pad to
        a pow2 width bucket and bound against the cache."""
        adapter_id = int(adapter_id)
        if adapter_id != 0 and self.n_adapters is None:
            raise ValueError("this engine's model has no adapter stacks "
                             "(multi_lora is off); adapter_id must be 0")
        if self.n_adapters is not None \
                and not 0 <= adapter_id < self.n_adapters:
            raise ValueError(f"adapter_id {adapter_id} outside "
                             f"[0, {self.n_adapters})")
        lens = [len(p) for p in prompts]
        if min(lens) == 0:
            raise ValueError("prompts must be non-empty")
        width = prompt_width_bucket(max(lens), self.max_seq)
        if max(lens) > width or width + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt {max(lens)} + budget {max_new_tokens} exceeds the "
                f"cache ({self.max_seq})")
        block = np.zeros((len(prompts), width), np.int32)
        for i, p in enumerate(prompts):
            block[i, :len(p)] = p
        return _Request(block, np.asarray(lens, np.int32), max_new_tokens,
                        float(temperature), top_k, eos_id, samples=samples,
                        top_p=top_p, adapter=adapter_id)

    def _reject_if_full_locked(self) -> None:
        """Caller holds self._lock. Raises EngineOverloaded (counted in
        the rejected stat) when max_pending is exhausted."""
        if (self.max_pending is not None
                and self._inflight >= self.max_pending):
            self._stats["rejected"] += 1
            raise EngineOverloaded(
                f"engine at capacity: {self._inflight} requests in "
                f"flight (max_pending={self.max_pending})")

    def take_admission_token(self) -> None:
        """Claim one unit of max_pending or raise EngineOverloaded.
        Callers that split ONE logical request into several chunk
        submits (the server's wider-than-slots path) take ONE token for
        the whole request and pass ``admitted=True`` to the submits —
        re-gating per chunk would reject an already-admitted request
        mid-flight after burning its earlier chunks' decode work."""
        with self._lock:
            self._reject_if_full_locked()
            self._inflight += 1

    def release_admission_token(self) -> None:
        with self._lock:
            self._inflight -= 1

    def at_capacity(self) -> bool:
        """Advisory (racy by nature): lets the HTTP layer 503 BEFORE
        committing response headers; the authoritative check is the
        token take in the submit paths."""
        with self._lock:
            return (self.max_pending is not None
                    and self._inflight >= self.max_pending)

    def reject_if_at_capacity(self) -> None:
        """Advisory shed WITHOUT claiming a token: raises
        EngineOverloaded (counted in the rejected stat, same as an
        authoritative take failure) when at capacity. For callers that
        must 503 before response headers but defer the real token take
        until their generator actually starts."""
        with self._lock:
            self._reject_if_full_locked()

    def _enqueue_and_wait(self, req: "_Request", timeout_s: float,
                          admitted: bool = False) -> "list[list[int]]":
        # The loop thread enforces the same deadline: a request whose
        # client gave up is dropped from the queue / its slots freed,
        # instead of decoding its full budget for nobody.
        if not admitted:
            self.take_admission_token()
        try:
            req.deadline = time.time() + timeout_s
            self._q.put(req)
            if not req.event.wait(timeout_s + 1.0):
                raise TimeoutError("generation did not finish in time")
            if req.error is not None:
                raise req.error
            return req.tokens
        finally:
            if not admitted:
                self.release_admission_token()

    def submit(self, prompts: "list[list[int]]", *, max_new_tokens: int,
               temperature: float = 0.0, top_k: "int | None" = None,
               top_p: "float | None" = None,
               eos_id: "int | None" = None, adapter_id: int = 0,
               timeout_s: float = 600.0,
               admitted: bool = False) -> "list[list[int]]":
        """Blocking: returns (n, max_new_tokens) token lists.
        ``admitted``: the caller already holds an admission token
        covering this submit (see take_admission_token)."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_samples(self, prompt: "list[int]", n: int, *,
                       max_new_tokens: int, temperature: float = 1.0,
                       top_k: "int | None" = None,
                       top_p: "float | None" = None,
                       eos_id: "int | None" = None, adapter_id: int = 0,
                       timeout_s: float = 600.0,
                       admitted: bool = False) -> "list[list[int]]":
        """n sampled continuations of ONE prompt for the price of one
        prefill: the prefilled cache row broadcasts across n slots and the
        rows diverge through per-row sampling noise. (With temperature 0
        all rows are the same greedy continuation — use submit().)"""
        if self._closed:
            raise RuntimeError("engine is closed")
        if not 1 <= n <= self.slots:
            raise ValueError(f"need 1..{self.slots} samples, got {n}")
        req = self._packed_request([prompt], max_new_tokens, temperature,
                                   top_k, eos_id, samples=n, top_p=top_p,
                                   adapter_id=adapter_id)
        return self._enqueue_and_wait(req, timeout_s, admitted)

    def submit_stream(self, prompts: "list[list[int]]", *,
                      max_new_tokens: int, temperature: float = 0.0,
                      top_k: "int | None" = None,
                      top_p: "float | None" = None,
                      eos_id: "int | None" = None, adapter_id: int = 0,
                      timeout_s: float = 600.0, admitted: bool = False):
        """Streaming submit(): returns an iterator of events.

        Incremental events are ``{"done": False, "rows": {row: [tok, ...]}}``
        — one per decode dispatch that produced tokens for this request
        (granularity = ``decode_block``; the first event carries each
        row's first token straight off the prefill logits, so
        time-to-first-token is prefill latency). The final event is
        ``{"done": True, "tokens": [[...]]}`` with exactly submit()'s
        return value (greedy exactness stays pinned to ``generate()``).
        Rows that hit eos stop producing deltas; the final tokens are
        eos-extended to the budget like submit()'s. Errors (deadline
        expiry, decode failure, shutdown) raise from the iterator."""
        if self._closed:
            raise RuntimeError("engine is closed")
        n = len(prompts)
        if n == 0 or n > self.slots:
            raise ValueError(f"need 1..{self.slots} prompts, got {n}")
        req = self._packed_request(prompts, max_new_tokens, temperature,
                                   top_k, eos_id, top_p=top_p,
                                   adapter_id=adapter_id)
        req.stream_q = queue.SimpleQueue()
        return self._stream_events(req, timeout_s, admitted)

    def _stream_events(self, req: "_Request", timeout_s: float,
                       admitted: bool = False):
        # Same deadline contract as _enqueue_and_wait: the loop thread
        # drops expired requests; this consumer gets the terminal marker
        # and raises the TimeoutError the loop recorded. The admission
        # token spans the generator's life — taken at first next() (no
        # iteration, no enqueue, no token), released in the finally.
        if not admitted:
            self.take_admission_token()
        try:
            yield from self._stream_events_inner(req, timeout_s)
        finally:
            if not admitted:
                self.release_admission_token()

    def _stream_events_inner(self, req: "_Request", timeout_s: float):
        req.deadline = time.time() + timeout_s
        self._q.put(req)
        hard = req.deadline + 1.0
        try:
            while True:
                try:
                    item = req.stream_q.get(
                        timeout=max(0.0, hard - time.time()))
                except queue.Empty:
                    raise TimeoutError("generation did not finish in time")
                if item is None:  # terminal: tokens ready or error
                    if req.error is not None:
                        raise req.error
                    yield {"done": True, "tokens": req.tokens}
                    return
                yield {"done": False, "rows": item}
        finally:
            # Consumer abandoned the stream (generator .close() on client
            # disconnect, or an exception in the consumer): expire the
            # request NOW so the loop reaps its queue entry / admission /
            # slots next iteration, instead of decoding the rest of the
            # budget for nobody.
            if req.tokens is None and req.error is None:
                req.deadline = 0.0

    def close(self) -> None:
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=60)

    def reset_stats(self) -> None:
        """Zero the counters (post-warmup: compile-dominated dispatches
        would poison the reported tokens_per_s). pcache_bytes is live
        state, not a counter — it survives the reset."""
        with self._lock:
            keep = self._stats["pcache_bytes"]
            for k in self._stats:
                self._stats[k] = type(self._stats[k])()
            self._stats["pcache_bytes"] = keep

    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
        s["tokens_per_s"] = (round(s["tokens"] / s["busy_s"], 2)
                             if s["busy_s"] > 0 else None)
        s["avg_active_slots"] = (round(s["slot_occupancy_sum"] / s["steps"],
                                       2) if s["steps"] else None)
        s["pcache_entries"] = len(self._pcache)
        return s

    # --- loop internals (single thread; owns all slot state) ------------

    def _free_slots(self) -> "list[int]":
        # A row that finished EARLY (eos) while its multi-row request is
        # still decoding stays owned: its collected tokens feed
        # _maybe_complete, so handing the slot to a new request would
        # clobber them (the stranger's tokens would surface in the
        # finished request's result, and the completion bookkeeping of
        # whichever finishes second corrupts the other's). Owner clears
        # at completion/failure — only then is the slot reusable.
        return [i for i in range(self.slots)
                if not self._active[i] and not self._reserved[i]
                and self._owner[i] is None]

    def _drain_queue(self, block: bool) -> bool:
        """Move queued requests into pending. Returns False on shutdown."""
        try:
            timeout = 0.2 if block else 0.0
            while True:
                req = self._q.get(block=block, timeout=timeout)
                if req is None:
                    return False
                self._pending.append(req)
                block = False  # only the first get may wait
        except queue.Empty:
            return True

    def _admit(self) -> None:
        """Admit pending requests. Chunked admissions advance ONE chunk
        per call, so an arriving long prompt delays in-flight decode by at
        most one chunk's latency, never the whole prefill. While a
        chunked admission is in flight, ONE short (single-shot) request
        may still slip in per call — no head-of-line blocking behind a
        long prefill when free slots exist."""
        if self._adm is not None:
            self._admission_step()
            self._admit_pending(allow_chunked=False, limit=1)
            return
        self._admit_pending(allow_chunked=True)

    def _admit_pending(self, *, allow_chunked: bool,
                       limit: "int | None" = None) -> None:
        admitted = 0
        i = 0
        while i < len(self._pending) and (limit is None
                                          or admitted < limit):
            req = self._pending[i]
            # The pow2 bucket is the admission unit: bucket rows beyond n
            # also land in free slots (they must not overwrite live rows),
            # so the fit check runs on nb BEFORE any device work.
            n, width = req.block.shape
            n_rows = req.samples if req.samples > 1 else n
            nb = min(_pow2_at_least(n_rows), self.slots)
            c = self.chunk_prefill
            # Prompt-cache probe (single-prompt requests): an exact hit
            # skips the prefill outright; a prefix hit appends only the
            # suffix — IF that suffix honors the same stall bound a
            # chunked prefill enforces and fits the cache depth.
            prompt = pkey = pentry = None
            if self.prompt_cache > 0 and n == 1:
                prompt = req.ptuple()
                if req.probe is None:
                    pkey, pentry = self._pcache_lookup(prompt, req.adapter)
                    if pkey is not None and len(pkey) < len(prompt):
                        g = _pow2_at_least(len(prompt) - len(pkey))
                        if (len(pkey) + g > self.max_seq
                                or (c is not None and g > c)):
                            pkey = pentry = None  # suffix too big
                    req.probe = (pkey, pentry)
                pkey, pentry = req.probe
            chunked = c is not None and width > c and pkey is None
            if chunked and not allow_chunked:
                i += 1  # long prompts wait for the in-flight one
                continue
            free = self._free_slots()
            if len(free) < nb:
                return  # strict FIFO on capacity: big requests don't starve
            self._pending.pop(i)
            admitted += 1
            if pkey is not None:
                exact = len(pkey) == len(prompt)
                with self._lock:
                    self._stats["pcache_hits" if exact
                                else "pcache_prefix_hits"] += 1
                try:
                    if exact:
                        small, last = pentry[0], pentry[1]
                    else:
                        small, last = self._pcache_extend(
                            pentry[0], prompt, len(pkey), req.adapter)
                        self._pcache_insert(prompt, small, last,
                                            req.adapter)
                    if req.samples > 1:
                        small, last = self._broadcast_rows(small, last, nb)
                    self._activate(req, free[:nb], n_rows, small, last)
                except Exception as e:  # noqa: BLE001 — fail the one request
                    req.error = e
                    req.signal()
                continue
            if prompt is not None:
                with self._lock:
                    self._stats["pcache_misses"] += 1
            if req.samples > 1:
                # Shared-prefix fan-out: prefill the ONE prompt row; the
                # broadcast to nb rows happens at activation/finalize.
                block, lens = req.block, req.lens
            else:
                block = np.zeros((nb, width), np.int32)
                block[:n] = req.block
                lens = np.concatenate(
                    [req.lens, np.ones((nb - n,), np.int32)])
            all_rows = free[:nb]
            if chunked:
                # Start a chunked admission: reserve the slots, run the
                # first chunk, and let subsequent loop iterations (with
                # decode steps in between) carry the rest.
                try:
                    small, _ = self._prefill(
                        self.params, jnp.asarray(block[:, :c]),
                        jnp.full((block.shape[0],), c, jnp.int32),
                        self._aid_arg(block.shape[0], req.adapter))
                except Exception as e:  # noqa: BLE001
                    req.error = e
                    req.signal()
                    continue
                for r in all_rows:
                    self._reserved[r] = True
                self._adm = {"req": req, "cache": small, "block": block,
                             "lens": lens, "pos": c, "rows": all_rows,
                             "n": n_rows}
                with self._lock:
                    self._stats["adm_chunks"] += 1
                return
            try:
                small, last = self._prefill(
                    self.params, jnp.asarray(block), jnp.asarray(lens),
                    self._aid_arg(block.shape[0], req.adapter))
                if prompt is not None:  # 1-row, pre-broadcast state
                    self._pcache_insert(prompt, small, last, req.adapter)
                if req.samples > 1:
                    small, last = self._broadcast_rows(small, last, nb)
                self._activate(req, all_rows, n_rows, small, last)
            except Exception as e:  # noqa: BLE001 — fail the one request
                req.error = e
                req.signal()
                continue

    def _admission_step(self) -> None:
        """One chunk of the in-flight admission (or its finalize)."""
        a = self._adm
        req, c = a["req"], self.chunk_prefill
        width = a["block"].shape[1]
        try:
            if a["pos"] < width:
                end = min(a["pos"] + c, width)
                a["cache"] = self._extend_chunk(
                    self.params, a["cache"],
                    jnp.asarray(a["block"][:, a["pos"]:end]),
                    self._aid_arg(a["block"].shape[0], req.adapter))
                a["pos"] = end
                with self._lock:
                    self._stats["adm_chunks"] += 1
                return
            # Finalize: every row consumed the padded width (short rows
            # carry junk K/V beyond their length). Reset each row's index
            # to len-1 (free rollback: junk becomes invisible) and decode
            # the row's LAST REAL token — recomputing its K/V in place and
            # yielding the exact first-token logits; index lands on len,
            # the engine's steady-state invariant.
            lens = a["lens"]
            cache = set_cache_index(a["cache"],
                                    jnp.asarray(lens - 1, jnp.int32))
            last_toks = a["block"][np.arange(len(lens)), lens - 1]
            cache, last = self._decode_logits(
                self.params, cache, jnp.asarray(last_toks),
                self._aid_arg(len(lens), req.adapter))
            if self.prompt_cache > 0 and a["block"].shape[0] == 1:
                # a["block"] row 0 == req.block row 0 by construction
                # (both admission paths copy it verbatim), so the
                # memoized key is THE key.
                self._pcache_insert(a["req"].ptuple(), cache, last,
                                    req.adapter)
            if req.samples > 1:
                cache, last = self._broadcast_rows(cache, last,
                                                   len(a["rows"]))
            for r in a["rows"]:
                self._reserved[r] = False
            self._adm = None
            self._activate(req, a["rows"], a["n"], cache, last)
        except Exception as e:  # noqa: BLE001 — fail the one request
            self._abort_admission(a, e)

    def _abort_admission(self, a: dict, err: Exception) -> None:
        """The one admission-abort path: release the reserved rows, null
        the in-flight record, and fail its request — in that order, so no
        exit leaves rows reserved for a request nobody is waiting on.
        Takes the record explicitly (NOT via self._adm): the finalize
        branch nulls self._adm before _activate, so an _activate failure
        must still reach the record it was admitting."""
        self._adm = None
        for r in a["rows"]:
            self._reserved[r] = False
        a["req"].error = err
        a["req"].signal()

    def _activate(self, req, all_rows, n, small_cache, last_logits) -> None:
        """Scatter an admitted small cache into the slot block and light
        up the rows (shared tail of both admission paths)."""
        rows = all_rows[:n]
        self._cache = self._scatter(
            self._cache, small_cache, jnp.asarray(all_rows, np.int32))
        nb = len(all_rows)
        temps = np.full((nb,), req.temp, np.float32)
        topks = np.full(
            (nb,), req.top_k if req.top_k else self.vocab, np.int32)
        topps = np.full(
            (nb,), 1.0 if req.top_p is None else req.top_p, np.float32)
        self._step_counter += 1
        first = np.asarray(self._first_sample(
            last_logits, jnp.asarray(temps), jnp.asarray(topks),
            jnp.asarray(topps), self._step_counter, self._base_key))
        req.slot_rows = rows
        for j, r in enumerate(rows):
            self._active[r] = True
            self._owner[r] = req
            self._aids[r] = req.adapter
            self._last_tok[r] = int(first[j])
            self._left[r] = req.budget - 1
            self._temps[r] = req.temp
            self._topks[r] = req.top_k if req.top_k else self.vocab
            self._topps[r] = 1.0 if req.top_p is None else req.top_p
            self._eos[r] = -1 if req.eos is None else int(req.eos)
            self._collected[r] = [int(first[j])]
        with self._lock:
            self._stats["requests"] += 1
            self._stats["tokens"] += len(rows)  # first sampled tokens
        if req.stream_q is not None:
            # First token per row streams immediately — it came from the
            # prefill's own logits, before any decode dispatch, so TTFT
            # is prefill latency, not prefill + a decode block.
            req.stream_q.put({j: [int(first[j])] for j in range(len(rows))})
        # eos on the very first token / budget 1 finishes immediately.
        for r in rows:
            if (self._left[r] <= 0
                    or (self._eos[r] >= 0
                        and self._last_tok[r] == self._eos[r])):
                self._finish_row(r)
        self._maybe_complete(req)

    def _finish_row(self, r: int) -> None:
        self._active[r] = False
        # Reset the slot's sampling temp: inactive rows still ride the
        # decode batch, and one stale temp>0 would disable the all-greedy
        # lax.cond fast path in _sample_rows for every later step until
        # the slot is reused.
        self._temps[r] = 0.0

    def _fail_request(self, req: "_Request", err: Exception) -> None:
        for r in req.slot_rows:
            self._active[r] = False
            self._temps[r] = 0.0  # keep the all-greedy fast path alive
            self._owner[r] = None
            self._collected[r] = []
        req.error = err
        req.signal()

    def _expire_deadlines(self) -> None:
        """Free resources of requests whose client stopped waiting."""
        now = time.time()
        expired = [r for r in self._pending if now > r.deadline]
        for req in expired:
            self._pending.remove(req)
            req.error = TimeoutError("expired while queued")
            req.signal()
        # The in-flight chunked admission too: its client may have given
        # up mid-prefill, and without this check the remaining chunks (and
        # the whole decode budget) would still run for nobody.
        if self._adm is not None and now > self._adm["req"].deadline:
            self._abort_admission(self._adm,
                                  TimeoutError("expired during admission"))
        for req in {self._owner[r] for r in range(self.slots)
                    if self._owner[r] is not None}:
            if now > req.deadline:
                self._fail_request(
                    req, TimeoutError("expired while decoding"))

    def _maybe_complete(self, req: "_Request") -> None:
        if any(self._active[r] for r in req.slot_rows):
            return
        pad_to = req.budget
        out = []
        for r in req.slot_rows:
            toks = self._collected[r][:pad_to]
            toks += [toks[-1]] * (pad_to - len(toks))  # eos-extend
            out.append(toks)
            self._owner[r] = None
            self._collected[r] = []
        req.tokens = out
        req.signal()

    def _loop(self) -> None:
        while True:
            any_active = bool(self._active.any())
            if not self._drain_queue(block=not any_active
                                     and not self._pending
                                     and self._adm is None):
                break  # shutdown sentinel
            self._expire_deadlines()
            self._admit()
            if not self._active.any():
                continue
            t0 = time.perf_counter()
            self._step_counter += 1
            k_tok = self.decode_block
            aids = (jnp.asarray(self._aids)
                    if self.n_adapters is not None else None)
            try:
                if k_tok == 1:
                    self._cache, nxt = self._decode_step(
                        self.params, self._cache,
                        jnp.asarray(self._last_tok),
                        jnp.asarray(self._temps),
                        jnp.asarray(self._topks),
                        jnp.asarray(self._topps),
                        self._step_counter, self._base_key, aids)
                    block = np.asarray(nxt)[None]          # (1, B)
                else:
                    self._cache, nxt = self._decode_block_step(
                        self.params, self._cache,
                        jnp.asarray(self._last_tok),
                        jnp.asarray(self._temps),
                        jnp.asarray(self._topks),
                        jnp.asarray(self._topps),
                        self._step_counter, self._base_key, k_tok, aids)
                    block = np.asarray(nxt)                # (K, B)
            except Exception as e:  # noqa: BLE001 — fail every live request
                for req in {self._owner[r] for r in range(self.slots)
                            if self._owner[r] is not None}:
                    req.error = e
                    req.signal()
                self._active[:] = False
                self._owner = [None] * self.slots
                continue
            dt = time.perf_counter() - t0
            n_active = int(self._active.sum())
            done_reqs = set()
            consumed = 0
            deltas: "dict[_Request, dict[int, list[int]]]" = {}
            for j in range(block.shape[0]):
                for r in range(self.slots):
                    if not self._active[r]:
                        continue  # finished mid-block: surplus discarded
                    tok = int(block[j, r])
                    self._last_tok[r] = tok
                    self._collected[r].append(tok)
                    self._left[r] -= 1
                    consumed += 1
                    owner = self._owner[r]
                    if owner is not None and owner.stream_q is not None:
                        deltas.setdefault(owner, {}).setdefault(
                            owner.slot_rows.index(r), []).append(tok)
                    if self._left[r] <= 0 or (self._eos[r] >= 0
                                              and tok == self._eos[r]):
                        self._finish_row(r)
                        done_reqs.add(owner)
            # Deltas flush BEFORE completion: the terminal marker from
            # signal() must be the stream's last item.
            for req, d in deltas.items():
                req.stream_q.put(d)
            with self._lock:
                # "steps" keeps its per-token meaning (device decode
                # steps) so the exported counter's unit survives the
                # k>1 default; "dispatches" counts device round-trips —
                # steps/dispatches is the realized block amortization.
                self._stats["steps"] += block.shape[0]
                self._stats["dispatches"] += 1
                self._stats["tokens"] += consumed
                self._stats["busy_s"] += dt
                self._stats["slot_occupancy_sum"] += (n_active
                                                      * block.shape[0])
            for req in done_reqs:
                self._maybe_complete(req)
        # Shutdown: fail anything still waiting — INCLUDING requests a
        # racing submit() enqueued behind the sentinel (they would
        # otherwise block their caller for the full submit timeout).
        err = RuntimeError("engine closed")
        try:
            while True:
                req = self._q.get(block=False)
                if req is not None:
                    self._pending.append(req)
        except queue.Empty:
            pass
        if self._adm is not None:
            self._pending.append(self._adm["req"])
            self._adm = None
        for req in self._pending:
            req.error = err
            req.signal()
        for req in {o for o in self._owner if o is not None}:
            req.error = err
            req.signal()
