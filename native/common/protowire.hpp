// Minimal protobuf wire-format encoder/decoder.
//
// The kubelet device-plugin API (v1beta1) is protobuf-over-gRPC; this image
// has libprotoc but hand-rolling the dozen fixed messages we exchange keeps
// the plugin dependency-free and the wire layer auditable. Field numbers are
// documented in native/tpu-device-plugin/deviceplugin.proto and mirrored by
// tests/dp_proto.py (the fake kubelet). Parity context: the reference's
// device plugin speaks the same gRPC API from Go (SURVEY.md §3.2 hot loop).

#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

namespace k3stpu::pw {

enum WireType : uint32_t {
  kVarint = 0,
  kFixed64 = 1,
  kLenDelim = 2,
  kFixed32 = 5,
};

// ---------------------------------------------------------------- encoding

inline void put_varint(std::string& out, uint64_t v) {
  while (v >= 0x80) {
    out.push_back(static_cast<char>((v & 0x7F) | 0x80));
    v >>= 7;
  }
  out.push_back(static_cast<char>(v));
}

inline void put_tag(std::string& out, uint32_t field, WireType wt) {
  put_varint(out, (static_cast<uint64_t>(field) << 3) | wt);
}

inline void put_string(std::string& out, uint32_t field, const std::string& s) {
  put_tag(out, field, kLenDelim);
  put_varint(out, s.size());
  out += s;
}

inline void put_message(std::string& out, uint32_t field,
                        const std::string& msg) {
  put_string(out, field, msg);
}

inline void put_uint(std::string& out, uint32_t field, uint64_t v) {
  put_tag(out, field, kVarint);
  put_varint(out, v);
}

inline void put_bool(std::string& out, uint32_t field, bool v) {
  if (v) put_uint(out, field, 1);
}

inline std::string map_entry(const std::string& key, const std::string& value) {
  std::string e;
  put_string(e, 1, key);
  put_string(e, 2, value);
  return e;
}

// ---------------------------------------------------------------- decoding

// Streaming field reader over a serialized message. Unknown fields skip
// cleanly, so the plugin tolerates newer kubelets.
class Reader {
 public:
  Reader(const char* data, size_t size) : p_(data), end_(data + size) {}
  explicit Reader(const std::string& s) : Reader(s.data(), s.size()) {}

  bool next(uint32_t& field, WireType& wt) {
    if (p_ >= end_) return false;
    uint64_t tag;
    if (!varint(tag)) return false;
    field = static_cast<uint32_t>(tag >> 3);
    wt = static_cast<WireType>(tag & 0x7);
    return true;
  }

  bool varint(uint64_t& v) {
    v = 0;
    int shift = 0;
    while (p_ < end_ && shift < 64) {
      uint8_t b = static_cast<uint8_t>(*p_++);
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return true;
      shift += 7;
    }
    return false;
  }

  bool bytes(std::string& out) {
    uint64_t len;
    if (!varint(len)) return false;
    // Compare against remaining bytes, not p_ + len: a crafted huge length
    // must not overflow the pointer arithmetic past end_.
    if (len > static_cast<uint64_t>(end_ - p_)) return false;
    out.assign(p_, static_cast<size_t>(len));
    p_ += len;
    return true;
  }

  bool skip(WireType wt) {
    switch (wt) {
      case kVarint: {
        uint64_t v;
        return varint(v);
      }
      case kFixed64:
        if (end_ - p_ < 8) return false;
        p_ += 8;
        return true;
      case kLenDelim: {
        std::string s;
        return bytes(s);
      }
      case kFixed32:
        if (end_ - p_ < 4) return false;
        p_ += 4;
        return true;
      default:
        return false;
    }
  }

 private:
  const char* p_;
  const char* end_;
};

inline bool parse_map_entry(const std::string& entry, std::string& key,
                            std::string& value) {
  Reader r(entry);
  uint32_t f;
  WireType wt;
  while (r.next(f, wt)) {
    if (f == 1 && wt == kLenDelim) {
      if (!r.bytes(key)) return false;
    } else if (f == 2 && wt == kLenDelim) {
      if (!r.bytes(value)) return false;
    } else if (!r.skip(wt)) {
      return false;
    }
  }
  return true;
}

}  // namespace k3stpu::pw
