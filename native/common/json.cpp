#include "json.hpp"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace k3stpu::json {

ValuePtr Value::make_null() { return std::make_shared<Value>(); }

ValuePtr Value::make_bool(bool b) {
  auto v = std::make_shared<Value>();
  v->type = Type::Bool;
  v->bool_v = b;
  return v;
}

ValuePtr Value::make_int(int64_t i) {
  auto v = std::make_shared<Value>();
  v->type = Type::Int;
  v->int_v = i;
  return v;
}

ValuePtr Value::make_string(const std::string& s) {
  auto v = std::make_shared<Value>();
  v->type = Type::String;
  v->str_v = s;
  return v;
}

ValuePtr Value::make_array() {
  auto v = std::make_shared<Value>();
  v->type = Type::Array;
  return v;
}

ValuePtr Value::make_object() {
  auto v = std::make_shared<Value>();
  v->type = Type::Object;
  return v;
}

ValuePtr Value::get(const std::string& key) const {
  if (type != Type::Object) return nullptr;
  for (const auto& [k, v] : obj_v)
    if (k == key) return v;
  return nullptr;
}

ValuePtr Value::set(const std::string& key, ValuePtr v) {
  for (auto& [k, existing] : obj_v) {
    if (k == key) {
      existing = v;
      return v;
    }
  }
  obj_v.emplace_back(key, v);
  return v;
}

ValuePtr Value::ensure_object(const std::string& key) {
  auto existing = get(key);
  if (existing && existing->is_object()) return existing;
  return set(key, make_object());
}

ValuePtr Value::ensure_array(const std::string& key) {
  auto existing = get(key);
  if (existing && existing->is_array()) return existing;
  return set(key, make_array());
}

namespace {

class Parser {
 public:
  explicit Parser(const std::string& text) : s_(text) {}

  ValuePtr parse_document() {
    auto v = parse_value();
    skip_ws();
    if (pos_ != s_.size()) fail("trailing characters after JSON document");
    return v;
  }

 private:
  const std::string& s_;
  size_t pos_ = 0;

  [[noreturn]] void fail(const std::string& msg) {
    throw ParseError(msg + " at byte " + std::to_string(pos_));
  }

  void skip_ws() {
    while (pos_ < s_.size() &&
           (s_[pos_] == ' ' || s_[pos_] == '\t' || s_[pos_] == '\n' ||
            s_[pos_] == '\r'))
      ++pos_;
  }

  char peek() {
    if (pos_ >= s_.size()) fail("unexpected end of input");
    return s_[pos_];
  }

  void expect(char c) {
    if (pos_ >= s_.size() || s_[pos_] != c)
      fail(std::string("expected '") + c + "'");
    ++pos_;
  }

  bool consume_lit(const char* lit) {
    size_t n = std::strlen(lit);
    if (s_.compare(pos_, n, lit) == 0) {
      pos_ += n;
      return true;
    }
    return false;
  }

  ValuePtr parse_value() {
    skip_ws();
    char c = peek();
    switch (c) {
      case '{':
        return parse_object();
      case '[':
        return parse_array();
      case '"':
        return Value::make_string(parse_string());
      case 't':
        if (consume_lit("true")) return Value::make_bool(true);
        fail("bad literal");
      case 'f':
        if (consume_lit("false")) return Value::make_bool(false);
        fail("bad literal");
      case 'n':
        if (consume_lit("null")) return Value::make_null();
        fail("bad literal");
      default:
        return parse_number();
    }
  }

  ValuePtr parse_object() {
    expect('{');
    auto obj = Value::make_object();
    skip_ws();
    if (peek() == '}') {
      ++pos_;
      return obj;
    }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(':');
      obj->obj_v.emplace_back(key, parse_value());
      skip_ws();
      if (peek() == ',') {
        ++pos_;
        continue;
      }
      expect('}');
      return obj;
    }
  }

  ValuePtr parse_array() {
    expect('[');
    auto arr = Value::make_array();
    skip_ws();
    if (peek() == ']') {
      ++pos_;
      return arr;
    }
    while (true) {
      arr->arr_v.push_back(parse_value());
      skip_ws();
      if (peek() == ',') {
        ++pos_;
        continue;
      }
      expect(']');
      return arr;
    }
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (pos_ >= s_.size()) fail("unterminated string");
      char c = s_[pos_++];
      if (c == '"') return out;
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      if (pos_ >= s_.size()) fail("unterminated escape");
      char e = s_[pos_++];
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          if (pos_ + 4 > s_.size()) fail("short \\u escape");
          unsigned cp = 0;
          for (int i = 0; i < 4; ++i) {
            char h = s_[pos_++];
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else fail("bad hex digit in \\u escape");
          }
          // Surrogate pair -> one code point.
          if (cp >= 0xD800 && cp <= 0xDBFF && pos_ + 6 <= s_.size() &&
              s_[pos_] == '\\' && s_[pos_ + 1] == 'u') {
            unsigned lo = 0;
            bool ok = true;
            for (int i = 0; i < 4; ++i) {
              char h = s_[pos_ + 2 + i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= h - '0';
              else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
              else { ok = false; break; }
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              pos_ += 6;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
          }
          // UTF-8 encode.
          if (cp < 0x80) {
            out.push_back(static_cast<char>(cp));
          } else if (cp < 0x800) {
            out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
          } else if (cp < 0x10000) {
            out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
          } else {
            out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
            out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
          }
          break;
        }
        default:
          fail("bad escape character");
      }
    }
  }

  ValuePtr parse_number() {
    size_t start = pos_;
    if (peek() == '-') ++pos_;
    while (pos_ < s_.size() && std::isdigit(static_cast<unsigned char>(s_[pos_])))
      ++pos_;
    bool is_double = false;
    if (pos_ < s_.size() && s_[pos_] == '.') {
      is_double = true;
      ++pos_;
      while (pos_ < s_.size() &&
             std::isdigit(static_cast<unsigned char>(s_[pos_])))
        ++pos_;
    }
    if (pos_ < s_.size() && (s_[pos_] == 'e' || s_[pos_] == 'E')) {
      is_double = true;
      ++pos_;
      if (pos_ < s_.size() && (s_[pos_] == '+' || s_[pos_] == '-')) ++pos_;
      while (pos_ < s_.size() &&
             std::isdigit(static_cast<unsigned char>(s_[pos_])))
        ++pos_;
    }
    if (pos_ == start || (pos_ == start + 1 && s_[start] == '-'))
      fail("malformed number");
    std::string tok = s_.substr(start, pos_ - start);
    auto v = std::make_shared<Value>();
    if (is_double) {
      v->type = Type::Double;
      v->dbl_v = std::stod(tok);
    } else {
      v->type = Type::Int;
      try {
        v->int_v = std::stoll(tok);
      } catch (const std::out_of_range&) {
        v->type = Type::Double;
        v->dbl_v = std::stod(tok);
      }
    }
    return v;
  }
};

void escape_into(const std::string& s, std::string& out) {
  out.push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out.push_back(static_cast<char>(c));
        }
    }
  }
  out.push_back('"');
}

void dump_into(const ValuePtr& v, std::string& out, int indent, int depth) {
  const std::string pad(static_cast<size_t>(indent) * depth, ' ');
  const std::string pad_in(static_cast<size_t>(indent) * (depth + 1), ' ');
  if (!v) {
    out += "null";
    return;
  }
  switch (v->type) {
    case Type::Null: out += "null"; break;
    case Type::Bool: out += v->bool_v ? "true" : "false"; break;
    case Type::Int: out += std::to_string(v->int_v); break;
    case Type::Double: {
      char buf[32];
      std::snprintf(buf, sizeof(buf), "%.17g", v->dbl_v);
      out += buf;
      break;
    }
    case Type::String: escape_into(v->str_v, out); break;
    case Type::Array: {
      if (v->arr_v.empty()) {
        out += "[]";
        break;
      }
      out += "[\n";
      for (size_t i = 0; i < v->arr_v.size(); ++i) {
        out += pad_in;
        dump_into(v->arr_v[i], out, indent, depth + 1);
        if (i + 1 < v->arr_v.size()) out += ",";
        out += "\n";
      }
      out += pad + "]";
      break;
    }
    case Type::Object: {
      if (v->obj_v.empty()) {
        out += "{}";
        break;
      }
      out += "{\n";
      for (size_t i = 0; i < v->obj_v.size(); ++i) {
        out += pad_in;
        escape_into(v->obj_v[i].first, out);
        out += ": ";
        dump_into(v->obj_v[i].second, out, indent, depth + 1);
        if (i + 1 < v->obj_v.size()) out += ",";
        out += "\n";
      }
      out += pad + "}";
      break;
    }
  }
}

}  // namespace

ValuePtr parse(const std::string& text) { return Parser(text).parse_document(); }

std::string dump(const ValuePtr& v, int indent) {
  std::string out;
  dump_into(v, out, indent, 0);
  out += "\n";
  return out;
}

}  // namespace k3stpu::json
