#include "grpc_transport.hpp"

#include <cstring>
#include <iostream>

#include <poll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace k3stpu::h2 {

namespace {

constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;

enum FrameType : uint8_t {
  kData = 0x0,
  kHeaders = 0x1,
  kPriority = 0x2,
  kRstStream = 0x3,
  kSettings = 0x4,
  kPing = 0x6,
  kGoaway = 0x7,
  kWindowUpdate = 0x8,
  kContinuation = 0x9,
};

enum Flags : uint8_t {
  kFlagEndStream = 0x1,
  kFlagAck = 0x1,
  kFlagEndHeaders = 0x4,
  kFlagPadded = 0x8,
  kFlagPriority = 0x20,
};

constexpr int64_t kDefaultWindow = 65535;

struct Frame {
  uint8_t type = 0;
  uint8_t flags = 0;
  uint32_t stream_id = 0;
  std::string payload;
};

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    // MSG_NOSIGNAL: a peer that closed (kubelet restart) must surface as an
    // error return, not a process-killing SIGPIPE — the re-register loop in
    // the plugin depends on surviving exactly this.
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_frame(int fd, Frame& f, size_t max_len = 1 << 24) {
  uint8_t hdr[9];
  if (!read_exact(fd, hdr, 9)) return false;
  size_t len = (static_cast<size_t>(hdr[0]) << 16) |
               (static_cast<size_t>(hdr[1]) << 8) | hdr[2];
  if (len > max_len) return false;
  f.type = hdr[3];
  f.flags = hdr[4];
  f.stream_id = ((static_cast<uint32_t>(hdr[5]) & 0x7F) << 24) |
                (static_cast<uint32_t>(hdr[6]) << 16) |
                (static_cast<uint32_t>(hdr[7]) << 8) | hdr[8];
  f.payload.resize(len);
  return len == 0 || read_exact(fd, f.payload.data(), len);
}

std::string frame_bytes(uint8_t type, uint8_t flags, uint32_t stream_id,
                        const std::string& payload) {
  std::string out;
  out.reserve(9 + payload.size());
  size_t len = payload.size();
  out.push_back(static_cast<char>((len >> 16) & 0xFF));
  out.push_back(static_cast<char>((len >> 8) & 0xFF));
  out.push_back(static_cast<char>(len & 0xFF));
  out.push_back(static_cast<char>(type));
  out.push_back(static_cast<char>(flags));
  out.push_back(static_cast<char>((stream_id >> 24) & 0x7F));
  out.push_back(static_cast<char>((stream_id >> 16) & 0xFF));
  out.push_back(static_cast<char>((stream_id >> 8) & 0xFF));
  out.push_back(static_cast<char>(stream_id & 0xFF));
  out += payload;
  return out;
}

// Strips padding/priority from a HEADERS payload to the header block.
bool header_block_of(const Frame& f, std::string& block) {
  size_t off = 0;
  size_t pad = 0;
  if (f.flags & kFlagPadded) {
    if (f.payload.empty()) return false;
    pad = static_cast<uint8_t>(f.payload[0]);
    off += 1;
  }
  if (f.flags & kFlagPriority) off += 5;
  if (off + pad > f.payload.size()) return false;
  block.assign(f.payload, off, f.payload.size() - off - pad);
  return true;
}

std::string be32(uint32_t v) {
  std::string s(4, '\0');
  s[0] = static_cast<char>((v >> 24) & 0xFF);
  s[1] = static_cast<char>((v >> 16) & 0xFF);
  s[2] = static_cast<char>((v >> 8) & 0xFF);
  s[3] = static_cast<char>(v & 0xFF);
  return s;
}

// gRPC message framing: flag byte + 4-byte big-endian length.
std::string grpc_frame(const std::string& msg) {
  std::string out;
  out.push_back('\0');
  out += be32(static_cast<uint32_t>(msg.size()));
  out += msg;
  return out;
}

// Incrementally extracts complete gRPC messages from a stream buffer.
bool pop_grpc_message(std::string& buf, std::string& msg) {
  if (buf.size() < 5) return false;
  uint32_t len = (static_cast<uint32_t>(static_cast<uint8_t>(buf[1])) << 24) |
                 (static_cast<uint32_t>(static_cast<uint8_t>(buf[2])) << 16) |
                 (static_cast<uint32_t>(static_cast<uint8_t>(buf[3])) << 8) |
                 static_cast<uint32_t>(static_cast<uint8_t>(buf[4]));
  if (buf.size() < 5 + len) return false;
  msg = buf.substr(5, len);
  buf.erase(0, 5 + len);
  return true;
}

int connect_unix(const std::string& path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

// Shared per-connection state for flow-controlled, mutex-serialized writes.
struct ConnWriter {
  explicit ConnWriter(int fd) : fd(fd) {}
  int fd;
  std::mutex mu;
  std::condition_variable cv;
  int64_t conn_window = kDefaultWindow;
  std::map<uint32_t, int64_t> stream_window;
  int32_t initial_window = kDefaultWindow;
  bool dead = false;

  bool raw_write(const std::string& bytes) {
    std::lock_guard<std::mutex> lock(mu);
    if (dead) return false;
    if (!write_all(fd, bytes.data(), bytes.size())) {
      dead = true;
      return false;
    }
    return true;
  }

  // DATA write with flow control; splits to the window when needed.
  bool write_data(uint32_t stream_id, const std::string& payload,
                  bool end_stream) {
    size_t off = 0;
    while (off < payload.size()) {
      std::unique_lock<std::mutex> lock(mu);
      cv.wait(lock, [&] {
        auto it = stream_window.find(stream_id);
        return dead || it == stream_window.end() ||
               (conn_window > 0 && it->second > 0);
      });
      auto it = stream_window.find(stream_id);
      if (dead || it == stream_window.end()) return false;  // peer gone
      size_t quota = static_cast<size_t>(std::min(conn_window, it->second));
      size_t n = std::min(payload.size() - off, quota);
      conn_window -= static_cast<int64_t>(n);
      it->second -= static_cast<int64_t>(n);
      bool last = (off + n) == payload.size();
      std::string fr =
          frame_bytes(kData, last && end_stream ? kFlagEndStream : 0,
                      stream_id, payload.substr(off, n));
      if (!write_all(fd, fr.data(), fr.size())) {
        dead = true;
        return false;
      }
      off += n;
    }
    return true;
  }

  void on_window_update(uint32_t stream_id, uint32_t increment) {
    std::lock_guard<std::mutex> lock(mu);
    if (stream_id == 0)
      conn_window += increment;
    else if (stream_window.count(stream_id))
      stream_window[stream_id] += increment;
    cv.notify_all();
  }

  void open_stream(uint32_t stream_id) {
    std::lock_guard<std::mutex> lock(mu);
    stream_window[stream_id] = initial_window;
  }

  void close_stream(uint32_t stream_id) {
    std::lock_guard<std::mutex> lock(mu);
    stream_window.erase(stream_id);
    cv.notify_all();
  }

  void apply_initial_window(int32_t new_size) {
    std::lock_guard<std::mutex> lock(mu);
    int64_t delta = static_cast<int64_t>(new_size) - initial_window;
    initial_window = new_size;
    for (auto& [_, w] : stream_window) w += delta;
    cv.notify_all();
  }

  void kill() {
    std::lock_guard<std::mutex> lock(mu);
    dead = true;
    cv.notify_all();
  }

  bool stream_alive(uint32_t stream_id) {
    std::lock_guard<std::mutex> lock(mu);
    return !dead && stream_window.count(stream_id) > 0;
  }
};

std::string settings_payload_empty() { return std::string(); }

void parse_settings(const Frame& f, ConnWriter& writer) {
  for (size_t off = 0; off + 6 <= f.payload.size(); off += 6) {
    uint16_t id = (static_cast<uint16_t>(static_cast<uint8_t>(f.payload[off]))
                   << 8) |
                  static_cast<uint8_t>(f.payload[off + 1]);
    uint32_t value =
        (static_cast<uint32_t>(static_cast<uint8_t>(f.payload[off + 2])) << 24) |
        (static_cast<uint32_t>(static_cast<uint8_t>(f.payload[off + 3])) << 16) |
        (static_cast<uint32_t>(static_cast<uint8_t>(f.payload[off + 4])) << 8) |
        static_cast<uint8_t>(f.payload[off + 5]);
    if (id == 0x4)  // SETTINGS_INITIAL_WINDOW_SIZE
      writer.apply_initial_window(static_cast<int32_t>(value));
  }
}

struct StreamState {
  Headers headers;
  std::string header_block;
  bool headers_done = false;
  std::string body;       // raw DATA bytes (gRPC-framed)
  bool end_stream = false;
  bool responded = false;
};

std::string path_of(const Headers& headers) {
  for (const auto& [n, v] : headers)
    if (n == ":path") return v;
  return "";
}

Headers response_headers() {
  return {{":status", "200"}, {"content-type", "application/grpc"}};
}

Headers trailers(int status, const std::string& message) {
  Headers t = {{"grpc-status", std::to_string(status)}};
  if (!message.empty()) t.emplace_back("grpc-message", message);
  return t;
}

}  // namespace

GrpcServer::~GrpcServer() { stop(); }

void GrpcServer::add_unary(const std::string& path, UnaryHandler handler) {
  unary_[path] = std::move(handler);
}

void GrpcServer::add_server_stream(const std::string& path,
                                   StreamHandler handler) {
  streams_[path] = std::move(handler);
}

bool GrpcServer::start(const std::string& socket_path) {
  socket_path_ = socket_path;
  // Bind under a temp name and rename only after listen(): the socket file
  // is how clients discover readiness, and a connect() in the bind->listen
  // window would get ECONNREFUSED.
  const std::string tmp_path = socket_path + ".tmp";
  ::unlink(socket_path.c_str());
  ::unlink(tmp_path.c_str());
  listen_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return false;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (tmp_path.size() >= sizeof(addr.sun_path)) {
    ::close(listen_fd_);
    listen_fd_ = -1;
    return false;
  }
  std::strncpy(addr.sun_path, tmp_path.c_str(), sizeof(addr.sun_path) - 1);
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(listen_fd_, 8) != 0 ||
      std::rename(tmp_path.c_str(), socket_path.c_str()) != 0) {
    ::close(listen_fd_);
    ::unlink(tmp_path.c_str());
    listen_fd_ = -1;
    return false;
  }
  accept_thread_ = std::thread([this] { accept_loop(); });
  return true;
}

void GrpcServer::stop() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (stopping_) return;
    stopping_ = true;
    // Unblock every connection reader so its thread can wind down; the
    // long-lived kubelet ListAndWatch connection would otherwise pin
    // stop() forever.
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  {
    // Connection threads are detached (a long-lived node would otherwise
    // accumulate unjoined thread stacks per kubelet reconnect); wait for
    // the counter they decrement on exit.
    std::unique_lock<std::mutex> lock(mu_);
    conn_cv_.wait(lock, [this] { return active_conns_ == 0; });
  }
  listen_fd_ = -1;
  if (!socket_path_.empty()) ::unlink(socket_path_.c_str());
}

void GrpcServer::accept_loop() {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;  // listener closed -> shutdown
    std::lock_guard<std::mutex> lock(mu_);
    if (stopping_) {
      ::close(fd);
      return;
    }
    conn_fds_.insert(fd);
    ++active_conns_;
    std::thread([this, fd] {
      handle_connection(fd);
      std::lock_guard<std::mutex> lock(mu_);
      --active_conns_;
      conn_cv_.notify_all();
    }).detach();
  }
}

void GrpcServer::handle_connection(int fd) {
  char preface[kPrefaceLen];
  if (!read_exact(fd, preface, kPrefaceLen) ||
      std::memcmp(preface, kPreface, kPrefaceLen) != 0) {
    ::close(fd);
    return;
  }
  auto writer = std::make_shared<ConnWriter>(fd);
  writer->raw_write(frame_bytes(kSettings, 0, 0, settings_payload_empty()));

  HpackDecoder decoder;
  std::map<uint32_t, StreamState> streams;
  // RPC handlers run detached (kubelet issues one Allocate per pod admission
  // on a connection that lives for weeks — unjoined thread stacks would
  // accumulate); this counter lets teardown wait for in-flight handlers.
  struct HandlerTracker {
    std::mutex mu;
    std::condition_variable cv;
    int active = 0;
  };
  auto tracker = std::make_shared<HandlerTracker>();
  auto spawn_handler = [tracker](std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lock(tracker->mu);
      ++tracker->active;
    }
    std::thread([tracker, fn = std::move(fn)] {
      fn();
      std::lock_guard<std::mutex> lock(tracker->mu);
      --tracker->active;
      tracker->cv.notify_all();
    }).detach();
  };

  Frame f;
  while (read_frame(fd, f)) {
    switch (f.type) {
      case kSettings:
        if (!(f.flags & kFlagAck)) {
          parse_settings(f, *writer);
          writer->raw_write(frame_bytes(kSettings, kFlagAck, 0, ""));
        }
        break;
      case kPing:
        if (!(f.flags & kFlagAck))
          writer->raw_write(frame_bytes(kPing, kFlagAck, 0, f.payload));
        break;
      case kWindowUpdate:
        if (f.payload.size() == 4) {
          uint32_t inc =
              ((static_cast<uint32_t>(static_cast<uint8_t>(f.payload[0])) & 0x7F)
               << 24) |
              (static_cast<uint32_t>(static_cast<uint8_t>(f.payload[1])) << 16) |
              (static_cast<uint32_t>(static_cast<uint8_t>(f.payload[2])) << 8) |
              static_cast<uint8_t>(f.payload[3]);
          writer->on_window_update(f.stream_id, inc);
        }
        break;
      case kHeaders:
      case kContinuation: {
        auto& st = streams[f.stream_id];
        if (f.type == kHeaders) {
          writer->open_stream(f.stream_id);
          std::string block;
          if (!header_block_of(f, block)) goto done;
          st.header_block += block;
          if (f.flags & kFlagEndStream) st.end_stream = true;
        } else {
          st.header_block += f.payload;
        }
        if (f.flags & kFlagEndHeaders) {
          if (!decoder.decode(
                  reinterpret_cast<const uint8_t*>(st.header_block.data()),
                  st.header_block.size(), st.headers))
            goto done;
          st.header_block.clear();
          st.headers_done = true;
        }
        break;
      }
      case kData: {
        auto& st = streams[f.stream_id];
        size_t off = 0, pad = 0;
        if (f.flags & kFlagPadded) {
          if (f.payload.empty()) goto done;
          pad = static_cast<uint8_t>(f.payload[0]);
          off = 1;
        }
        if (off + pad <= f.payload.size())
          st.body.append(f.payload, off, f.payload.size() - off - pad);
        if (f.flags & kFlagEndStream) st.end_stream = true;
        // Replenish receive windows so long-lived connections never stall.
        if (!f.payload.empty()) {
          writer->raw_write(frame_bytes(
              kWindowUpdate, 0, 0,
              be32(static_cast<uint32_t>(f.payload.size()))));
          writer->raw_write(frame_bytes(
              kWindowUpdate, 0, f.stream_id,
              be32(static_cast<uint32_t>(f.payload.size()))));
        }
        break;
      }
      case kRstStream:
        writer->close_stream(f.stream_id);
        streams.erase(f.stream_id);
        break;
      case kGoaway:
        goto done;
      default:
        break;  // PRIORITY etc.: ignore
    }

    // Dispatch streams whose request is complete. State moves out of the map
    // (long-lived connections would otherwise accumulate one StreamState per
    // RPC forever), and all handlers run on their own thread so the reader
    // loop keeps servicing WINDOW_UPDATE/PING — a unary response larger than
    // the flow-control window must not deadlock against its own reader.
    for (auto it = streams.begin(); it != streams.end();) {
      if (!it->second.headers_done || !it->second.end_stream) {
        ++it;
        continue;
      }
      const uint32_t stream_id = it->first;
      StreamState st = std::move(it->second);
      it = streams.erase(it);

      std::string msg;
      pop_grpc_message(st.body, msg);
      const std::string rpc = path_of(st.headers);

      auto send_response_headers = [writer, stream_id] {
        writer->raw_write(frame_bytes(kHeaders, kFlagEndHeaders, stream_id,
                                      encode_headers(response_headers())));
      };
      auto send_trailers = [writer, stream_id](int status,
                                               const std::string& message) {
        writer->raw_write(frame_bytes(kHeaders,
                                      kFlagEndHeaders | kFlagEndStream,
                                      stream_id,
                                      encode_headers(trailers(status, message))));
        writer->close_stream(stream_id);
      };

      if (auto uit = unary_.find(rpc); uit != unary_.end()) {
        UnaryHandler handler = uit->second;
        spawn_handler([handler, msg, writer, stream_id,
                       send_response_headers, send_trailers] {
          try {
            std::string resp = handler(msg);
            send_response_headers();
            writer->write_data(stream_id, grpc_frame(resp), false);
            send_trailers(kOk, "");
          } catch (const GrpcError& e) {
            send_response_headers();
            send_trailers(e.status, e.message);
          } catch (const std::exception& e) {
            send_response_headers();
            send_trailers(kUnknown, e.what());
          }
        });
      } else if (auto sit = streams_.find(rpc); sit != streams_.end()) {
        StreamHandler handler = sit->second;
        spawn_handler([handler, msg, writer, stream_id,
                       send_response_headers, send_trailers] {
          send_response_headers();
          StreamCtx ctx;
          ctx.write = [writer, stream_id](const std::string& m) {
            return writer->write_data(stream_id, grpc_frame(m), false);
          };
          ctx.alive = [writer, stream_id] {
            return writer->stream_alive(stream_id);
          };
          try {
            handler(msg, ctx);
            send_trailers(kOk, "");
          } catch (const GrpcError& e) {
            send_trailers(e.status, e.message);
          } catch (const std::exception& e) {
            send_trailers(kUnknown, e.what());
          }
        });
      } else {
        send_response_headers();
        send_trailers(kUnimplemented, "unknown method " + rpc);
      }
    }
  }
done:
  writer->kill();
  ::shutdown(fd, SHUT_RDWR);
  {
    std::unique_lock<std::mutex> lock(tracker->mu);
    tracker->cv.wait(lock, [&] { return tracker->active == 0; });
  }
  {
    // Drop from the live set before close: fd numbers are reused, and a
    // later stop() must not shutdown() whoever inherited this number.
    std::lock_guard<std::mutex> lock(mu_);
    conn_fds_.erase(fd);
  }
  ::close(fd);
}

UnaryResult grpc_unary_call(const std::string& socket_path,
                            const std::string& rpc_path,
                            const std::string& request, int timeout_ms) {
  UnaryResult result;
  int fd = connect_unix(socket_path);
  if (fd < 0) return result;

  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));

  std::string out(kPreface, kPrefaceLen);
  out += frame_bytes(kSettings, 0, 0, "");
  Headers req_headers = {
      {":method", "POST"},       {":scheme", "http"},
      {":path", rpc_path},       {":authority", "localhost"},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
  };
  out += frame_bytes(kHeaders, kFlagEndHeaders, 1, encode_headers(req_headers));
  out += frame_bytes(kData, kFlagEndStream, 1, grpc_frame(request));
  if (!write_all(fd, out.data(), out.size())) {
    ::close(fd);
    return result;
  }

  HpackDecoder decoder;
  std::string body;
  std::string header_block;
  bool in_headers = false;
  bool end_stream_seen = false;  // END_STREAM rides HEADERS, not CONTINUATION
  Frame f;
  while (read_frame(fd, f)) {
    if (f.type == kSettings && !(f.flags & kFlagAck)) {
      write_all(fd, frame_bytes(kSettings, kFlagAck, 0, "").data(), 9);
    } else if (f.type == kPing && !(f.flags & kFlagAck)) {
      auto pong = frame_bytes(kPing, kFlagAck, 0, f.payload);
      write_all(fd, pong.data(), pong.size());
    } else if (f.stream_id == 1 &&
               (f.type == kHeaders || f.type == kContinuation)) {
      if (f.type == kHeaders) {
        std::string block;
        if (!header_block_of(f, block)) break;
        header_block += block;
        if (f.flags & kFlagEndStream) end_stream_seen = true;
      } else {
        header_block += f.payload;
      }
      in_headers = true;
      if (f.flags & kFlagEndHeaders) {
        Headers hs;
        if (!decoder.decode(
                reinterpret_cast<const uint8_t*>(header_block.data()),
                header_block.size(), hs))
          break;
        header_block.clear();
        in_headers = false;
        for (const auto& [n, v] : hs) {
          if (n == "grpc-status") {
            result.grpc_status = std::atoi(v.c_str());
            result.transport_ok = true;
          } else if (n == "grpc-message") {
            result.message = v;
          }
        }
        if (end_stream_seen) break;  // trailers received
      }
    } else if (f.stream_id == 1 && f.type == kData) {
      size_t off = 0, pad = 0;
      if (f.flags & kFlagPadded) {
        pad = static_cast<uint8_t>(f.payload[0]);
        off = 1;
      }
      if (off + pad <= f.payload.size())
        body.append(f.payload, off, f.payload.size() - off - pad);
      // Replenish flow-control windows or responses beyond 64KiB stall the
      // sender (and this call) until the socket timeout.
      if (!f.payload.empty()) {
        auto inc = be32(static_cast<uint32_t>(f.payload.size()));
        auto w0 = frame_bytes(kWindowUpdate, 0, 0, inc);
        auto w1 = frame_bytes(kWindowUpdate, 0, 1, inc);
        write_all(fd, w0.data(), w0.size());
        write_all(fd, w1.data(), w1.size());
      }
    } else if (f.type == kGoaway || f.type == kRstStream) {
      break;
    }
  }
  ::close(fd);
  (void)in_headers;
  std::string msg;
  if (pop_grpc_message(body, msg)) result.response = msg;
  return result;
}

}  // namespace k3stpu::h2
