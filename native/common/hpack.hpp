// HPACK (RFC 7541) decode via the system libnghttp2.so.14, encode by hand.
//
// Only the runtime .so is baked into this image (no dev headers), so the few
// stable entry points we need are declared here directly. All nghttp2 types
// involved are opaque pointers except nghttp2_nv, whose layout has been fixed
// since nghttp2 1.0. Encoding always uses "literal header field without
// indexing / new name" representations — spec-valid, stateless, and every
// HTTP/2 peer must accept it, so no deflater state is needed.

#pragma once

#include <cstddef>
#include <cstdint>
#include <string>
#include <utility>
#include <vector>

extern "C" {
typedef struct nghttp2_hd_inflater nghttp2_hd_inflater;

typedef struct {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
} nghttp2_nv;

int nghttp2_hd_inflate_new(nghttp2_hd_inflater** inflater_ptr);
void nghttp2_hd_inflate_del(nghttp2_hd_inflater* inflater);
ssize_t nghttp2_hd_inflate_hd2(nghttp2_hd_inflater* inflater,
                               nghttp2_nv* nv_out, int* inflate_flags,
                               const uint8_t* in, size_t inlen, int in_final);
int nghttp2_hd_inflate_end_headers(nghttp2_hd_inflater* inflater);
}

namespace k3stpu::h2 {

inline constexpr int kInflateFinal = 0x01;  // NGHTTP2_HD_INFLATE_FINAL
inline constexpr int kInflateEmit = 0x02;   // NGHTTP2_HD_INFLATE_EMIT

using Headers = std::vector<std::pair<std::string, std::string>>;

class HpackDecoder {
 public:
  HpackDecoder() { nghttp2_hd_inflate_new(&inflater_); }
  ~HpackDecoder() { nghttp2_hd_inflate_del(inflater_); }
  HpackDecoder(const HpackDecoder&) = delete;
  HpackDecoder& operator=(const HpackDecoder&) = delete;

  // Decodes one complete header block; returns false on malformed input.
  bool decode(const uint8_t* data, size_t len, Headers& out);

 private:
  nghttp2_hd_inflater* inflater_ = nullptr;
};

// Appends one header as a literal-without-indexing representation.
void encode_header(std::string& out, const std::string& name,
                   const std::string& value);

inline std::string encode_headers(const Headers& headers) {
  std::string out;
  for (const auto& [n, v] : headers) encode_header(out, n, v);
  return out;
}

}  // namespace k3stpu::h2
