// Minimal ordered JSON parser/serializer for the K3S-TPU native components.
//
// Why hand-rolled: the OCI runtime shim must rewrite a container's
// config.json byte-faithfully enough that runc accepts it, and this image has
// no C++ JSON library baked in. Insertion order is preserved (objects are
// vectors of pairs) so patched specs diff cleanly against their inputs.
// Parity note: the reference's nvidia-container-runtime does the same job
// with Go's encoding/json (reference README.md:164 describes the behavior;
// see SURVEY.md §2b #7).

#pragma once

#include <cstdint>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace k3stpu::json {

class Value;
using ValuePtr = std::shared_ptr<Value>;

enum class Type { Null, Bool, Int, Double, String, Array, Object };

class Value {
 public:
  Type type = Type::Null;
  bool bool_v = false;
  int64_t int_v = 0;
  double dbl_v = 0.0;
  std::string str_v;
  std::vector<ValuePtr> arr_v;
  std::vector<std::pair<std::string, ValuePtr>> obj_v;

  static ValuePtr make_null();
  static ValuePtr make_bool(bool b);
  static ValuePtr make_int(int64_t i);
  static ValuePtr make_string(const std::string& s);
  static ValuePtr make_array();
  static ValuePtr make_object();

  bool is_object() const { return type == Type::Object; }
  bool is_array() const { return type == Type::Array; }
  bool is_string() const { return type == Type::String; }

  // Object helpers. get() returns nullptr when missing or not an object.
  ValuePtr get(const std::string& key) const;
  // Sets (replacing any existing entry) and returns the stored value.
  ValuePtr set(const std::string& key, ValuePtr v);
  // Returns the child object/array at key, creating it if absent.
  ValuePtr ensure_object(const std::string& key);
  ValuePtr ensure_array(const std::string& key);

  std::string as_string(const std::string& fallback = "") const {
    return type == Type::String ? str_v : fallback;
  }
};

struct ParseError : std::runtime_error {
  explicit ParseError(const std::string& msg) : std::runtime_error(msg) {}
};

// Parses a complete JSON document; throws ParseError on malformed input.
ValuePtr parse(const std::string& text);

// Serializes with 2-space indentation (stable output for spec-diff tests).
std::string dump(const ValuePtr& v, int indent = 2);

}  // namespace k3stpu::json
