#include "hpack.hpp"

namespace k3stpu::h2 {

bool HpackDecoder::decode(const uint8_t* data, size_t len, Headers& out) {
  size_t pos = 0;
  for (;;) {
    nghttp2_nv nv;
    int flags = 0;
    ssize_t consumed = nghttp2_hd_inflate_hd2(inflater_, &nv, &flags,
                                              data + pos, len - pos,
                                              /*in_final=*/1);
    if (consumed < 0) return false;
    pos += static_cast<size_t>(consumed);
    if (flags & kInflateEmit) {
      out.emplace_back(
          std::string(reinterpret_cast<char*>(nv.name), nv.namelen),
          std::string(reinterpret_cast<char*>(nv.value), nv.valuelen));
    }
    if (flags & kInflateFinal) {
      nghttp2_hd_inflate_end_headers(inflater_);
      return true;
    }
    if (consumed == 0 && !(flags & kInflateEmit)) return false;  // stuck
  }
}

namespace {

// HPACK integer with a 7-bit prefix (string length encoding, H bit clear).
void put_len(std::string& out, size_t n) {
  if (n < 0x7F) {
    out.push_back(static_cast<char>(n));
    return;
  }
  out.push_back(0x7F);
  n -= 0x7F;
  while (n >= 0x80) {
    out.push_back(static_cast<char>((n & 0x7F) | 0x80));
    n >>= 7;
  }
  out.push_back(static_cast<char>(n));
}

}  // namespace

void encode_header(std::string& out, const std::string& name,
                   const std::string& value) {
  out.push_back(0x00);  // literal without indexing, new name
  put_len(out, name.size());
  out += name;
  put_len(out, value.size());
  out += value;
}

}  // namespace k3stpu::h2
