#include "chips.hpp"

#include <algorithm>
#include <cstdlib>
#include <fstream>
#include <ctime>
#include <sstream>

#include <dirent.h>
#include <sys/stat.h>

#include "json.hpp"

namespace k3stpu {

namespace {

std::string read_trimmed(const std::string& path) {
  std::ifstream f(path);
  if (!f) return "";
  std::string s;
  std::getline(f, s);
  while (!s.empty() && (s.back() == '\n' || s.back() == '\r' || s.back() == ' '))
    s.pop_back();
  return s;
}

std::vector<std::string> list_dir(const std::string& path) {
  std::vector<std::string> names;
  DIR* d = opendir(path.c_str());
  if (!d) return names;
  while (dirent* e = readdir(d)) {
    std::string n = e->d_name;
    if (n != "." && n != "..") names.push_back(n);
  }
  closedir(d);
  std::sort(names.begin(), names.end());
  return names;
}

bool exists(const std::string& path) {
  struct stat st{};
  return ::stat(path.c_str(), &st) == 0;
}

std::string lower(std::string s) {
  std::transform(s.begin(), s.end(), s.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return s;
}

std::string generation_for(const std::string& device_id) {
  if (device_id == "0x0027") return "tpu-v2/v3";
  if (device_id == "0x005e") return "tpu-v4";
  if (device_id == "0x0062") return "tpu-v5e";
  if (device_id == "0x0063") return "tpu-v5p";
  if (device_id == "0x006f") return "tpu-v6e";
  return "tpu-unknown";
}

}  // namespace

std::string host_root(const std::string& override_root) {
  if (!override_root.empty()) return override_root;
  const char* env = std::getenv(kHostRootEnv);
  return env && *env ? env : "/";
}

std::vector<TpuChip> enumerate_chips(const std::string& root_in) {
  std::string root = host_root(root_in);
  if (root.back() == '/') root.pop_back();
  std::vector<TpuChip> chips;

  // accel nodes sorted numerically: accel0, accel1, ... accel10.
  std::vector<std::string> accel;
  for (const auto& name : list_dir(root + "/dev")) {
    if (name.rfind("accel", 0) == 0 &&
        name.find_first_not_of("0123456789", 5) == std::string::npos &&
        name.size() > 5)
      accel.push_back(name);
  }
  std::sort(accel.begin(), accel.end(), [](const auto& a, const auto& b) {
    return std::stoi(a.substr(5)) < std::stoi(b.substr(5));
  });

  std::vector<std::string> vfio;
  for (const auto& name : list_dir(root + "/dev/vfio")) {
    if (!name.empty() &&
        name.find_first_not_of("0123456789") == std::string::npos)
      vfio.push_back(name);
  }
  std::sort(vfio.begin(), vfio.end(), [](const auto& a, const auto& b) {
    return std::stoi(a) < std::stoi(b);
  });

  int idx = 0;
  const std::string pci_dir = root + "/sys/bus/pci/devices";
  for (const auto& bdf : list_dir(pci_dir)) {
    const std::string dev_dir = pci_dir + "/" + bdf;
    if (lower(read_trimmed(dev_dir + "/vendor")) != kGoogleVendorId) continue;

    TpuChip chip;
    chip.index = idx;
    chip.pci_address = bdf;
    chip.device_id = lower(read_trimmed(dev_dir + "/device"));
    chip.generation = generation_for(chip.device_id);
    const std::string numa = read_trimmed(dev_dir + "/numa_node");
    chip.numa_node = numa.empty() ? -1 : std::atoi(numa.c_str());

    // Chips consume accel nodes first (in index order); any remaining chips
    // map onto the vfio groups starting from vfio[0].
    if (static_cast<size_t>(idx) < accel.size()) {
      chip.dev_paths = {"/dev/" + accel[idx]};
    } else if (static_cast<size_t>(idx) - accel.size() < vfio.size()) {
      chip.dev_paths = {"/dev/vfio/" + vfio[idx - accel.size()],
                        "/dev/vfio/vfio"};
    }
    // ICI coords: a `tpu_coords` sysfs attribute ("x,y") is ground truth
    // when present (driver/provisioning-exposed adjacency).
    const std::string coords = read_trimmed(dev_dir + "/tpu_coords");
    size_t comma = coords.find(',');
    if (comma != std::string::npos) {
      const std::string xs = coords.substr(0, comma);
      const std::string ys = coords.substr(comma + 1);
      // Digits-only on both halves (atoi would silently yield (0,0));
      // length-capped so the bounds check below can't overflow.
      if (!xs.empty() && !ys.empty() && xs.size() <= 6 && ys.size() <= 6 &&
          xs.find_first_not_of("0123456789") == std::string::npos &&
          ys.find_first_not_of("0123456789") == std::string::npos) {
        chip.coord_x = std::atoi(xs.c_str());
        chip.coord_y = std::atoi(ys.c_str());
      }
    }

    chips.push_back(std::move(chip));
    ++idx;
  }

  // Coords are only trusted within the tray extent: an n-chip tray fits in
  // an n x n grid, and the allocator's rectangle search is O(extent^4) —
  // out-of-range values (junk, or global slice coords) would wedge it.
  // Rejected or absent coords fall back to row-major tray defaults (v5e
  // host trays are wired row-major), so adjacency is always defined.
  const int n = static_cast<int>(chips.size());
  const int cols = tray_cols(chips.size());
  for (auto& chip : chips) {
    if (chip.coord_x < 0 || chip.coord_y < 0 ||
        chip.coord_x >= n || chip.coord_y >= n) {
      chip.coord_x = chip.index % cols;
      chip.coord_y = chip.index / cols;
    }
  }
  return chips;
}

std::string find_libtpu(const std::string& root_in) {
  std::string root = host_root(root_in);
  if (root.back() == '/') root.pop_back();
  for (const char* rel :
       {"/usr/lib/libtpu.so", "/usr/local/lib/libtpu.so", "/lib/libtpu.so",
        "/usr/lib/x86_64-linux-gnu/libtpu.so"}) {
    if (exists(root + rel)) return rel;
  }
  return "";
}

std::string topology_for(size_t n) {
  switch (n) {
    case 0: return "0";
    case 1: return "1x1";
    case 2: return "1x2";
    case 4: return "2x2";
    case 8: return "2x4";
    case 16: return "4x4";
    default: return "1x" + std::to_string(n);
  }
}

int cores_per_chip(const std::string& generation) {
  if (generation == "tpu-v2/v3" || generation == "tpu-v4" ||
      generation == "tpu-v5p")
    return 2;
  return 1;  // v5e, v6e, unknown: one TensorCore per chip
}

int tray_cols(size_t n) {
  switch (n) {
    case 4: return 2;   // 2x2
    case 8: return 4;   // 2x4
    case 16: return 4;  // 4x4
    default: return n ? static_cast<int>(n) : 1;  // 1xN line
  }
}

long long hbm_bytes_for(const std::string& generation) {
  constexpr long long kGiB = 1024LL * 1024 * 1024;
  // v2 (16 GiB) and v3 (32 GiB) share a PCI device id, so the merged
  // bucket would be confidently wrong for half the hardware: report
  // unknown ("n/a") rather than a number known to be wrong.
  if (generation == "tpu-v2/v3") return -1;
  if (generation == "tpu-v4") return 32 * kGiB;
  if (generation == "tpu-v5e") return 16 * kGiB;
  if (generation == "tpu-v5p") return 95 * kGiB;
  if (generation == "tpu-v6e") return 32 * kGiB;
  return -1;
}

namespace {

long long read_ll(const std::string& path) {
  const std::string s = read_trimmed(path);
  if (s.empty() || s.find_first_not_of("0123456789") != std::string::npos)
    return -1;
  return std::atoll(s.c_str());
}

}  // namespace

void fill_telemetry(std::vector<TpuChip>& chips, const std::string& root_in) {
  std::string root = host_root(root_in);
  if (root.back() == '/') root.pop_back();

  // Workload-exported drop file, keyed by chip index. Best-effort: a
  // missing, stale, or malformed file simply leaves fields at -1. Staleness
  // is judged by the writer's own "ts": a run-to-completion probe (or a
  // crashed server) leaves its last snapshot behind, and presenting hours-
  // old bytes_in_use as live would be worse than "n/a".
  constexpr long long kMaxDropAgeS = 120;
  struct Live {
    long long used = -1, total = -1;
    int duty = -1;
    bool est = false;
  };
  std::vector<Live> live;
  std::ifstream f(root + kMetricsDropPath);
  if (f) {
    std::stringstream ss;
    ss << f.rdbuf();
    try {
      auto doc = json::parse(ss.str());
      // Drop-file writers are external (Python json emits computed
      // numbers as doubles): accept Int or Double for every numeric
      // field, not just the ones our own telemetry.py happens to write.
      auto as_ll = [](const auto& v) -> long long {
        return v->type == json::Type::Double
                   ? static_cast<long long>(v->dbl_v)
                   : v->int_v;
      };
      bool fresh = false;
      if (doc && doc->is_object()) {
        if (auto ts = doc->get("ts")) {
          const long long now =
              static_cast<long long>(::time(nullptr));
          const long long t = as_ll(ts);
          fresh = t > 0 && now - t <= kMaxDropAgeS;
        }
      }
      auto devs = doc && doc->is_object() && fresh
                      ? doc->get("devices") : nullptr;
      if (devs && devs->is_array()) {
        for (const auto& d : devs->arr_v) {
          if (!d || !d->is_object()) continue;
          Live l;
          if (auto v = d->get("bytes_in_use")) l.used = as_ll(v);
          if (auto v = d->get("bytes_limit")) l.total = as_ll(v);
          if (auto v = d->get("source"))
            l.est = v->is_string() && v->str_v == "live_arrays";
          if (auto v = d->get("duty_cycle_pct"))
            l.duty = static_cast<int>(as_ll(v));
          long long idx = -1;
          if (auto v = d->get("index")) idx = as_ll(v);
          if (idx >= 0 && idx < 4096) {
            if (live.size() <= static_cast<size_t>(idx))
              live.resize(idx + 1);
            live[idx] = l;
          }
        }
      }
    } catch (const json::ParseError&) {
      // malformed drop file: ignore, fields stay n/a
    }
  }

  const std::string pci_dir = root + "/sys/bus/pci/devices";
  for (auto& chip : chips) {
    const std::string dev_dir = pci_dir + "/" + chip.pci_address;
    // 1) driver sysfs attributes (authoritative when present)
    chip.mem_used_bytes = read_ll(dev_dir + "/tpu_mem_used_bytes");
    chip.mem_total_bytes = read_ll(dev_dir + "/tpu_mem_total_bytes");
    long long duty = read_ll(dev_dir + "/tpu_duty_cycle_pct");
    chip.duty_cycle_pct = duty > 100 ? -1 : static_cast<int>(duty);
    // 2) workload drop file
    if (static_cast<size_t>(chip.index) < live.size()) {
      const Live& l = live[chip.index];
      if (chip.mem_used_bytes < 0) {
        chip.mem_used_bytes = l.used;
        chip.mem_estimated = l.used >= 0 && l.est;
      }
      if (chip.mem_total_bytes < 0) chip.mem_total_bytes = l.total;
      if (chip.duty_cycle_pct < 0 && l.duty >= 0 && l.duty <= 100)
        chip.duty_cycle_pct = l.duty;
    }
    // 3) generation table for the capacity column
    if (chip.mem_total_bytes < 0)
      chip.mem_total_bytes = hbm_bytes_for(chip.generation);
  }
}

}  // namespace k3stpu
