// Minimal gRPC-over-HTTP/2 transport on unix sockets.
//
// Purpose-built for the kubelet device-plugin protocol (SURVEY.md §3.2): a
// server side for DevicePlugin (unary + server-streaming ListAndWatch — the
// long-lived "hot loop" of the reference stack) and a client side for the
// one-shot Registration call. No TLS (kubelet device-plugin sockets are
// plaintext unix sockets), no compression, HPACK via the system libnghttp2.
//
// Threading: one reader thread per accepted connection; server-stream
// handlers run on their own thread and write through a mutex, so Allocate
// stays responsive while ListAndWatch blocks awaiting device-state changes.

#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "hpack.hpp"

namespace k3stpu::h2 {

// gRPC status codes we use.
inline constexpr int kOk = 0;
inline constexpr int kUnknown = 2;
inline constexpr int kUnimplemented = 12;

struct GrpcError {
  int status;
  std::string message;
};

// Handle a server-stream gives to its handler thread.
struct StreamCtx {
  // Writes one message; returns false once the peer is gone.
  std::function<bool(const std::string& msg)> write;
  // Cheap liveness probe so handlers blocked on their own conditions can
  // poll for peer disconnect without emitting anything.
  std::function<bool()> alive;
};

// Unary: request bytes in, response bytes out; throw GrpcError to fail.
using UnaryHandler = std::function<std::string(const std::string& request)>;

// Server-streaming: write() as many times as needed, return to close with OK.
using StreamHandler =
    std::function<void(const std::string& request, const StreamCtx& ctx)>;

class GrpcServer {
 public:
  GrpcServer() = default;
  ~GrpcServer();
  GrpcServer(const GrpcServer&) = delete;
  GrpcServer& operator=(const GrpcServer&) = delete;

  void add_unary(const std::string& path, UnaryHandler handler);
  void add_server_stream(const std::string& path, StreamHandler handler);

  // Binds the unix socket (unlinking any stale file) and starts the accept
  // loop on a background thread. Returns false when bind/listen fails.
  bool start(const std::string& socket_path);
  void stop();
  bool running() const { return listen_fd_ >= 0; }

 private:
  void accept_loop();
  void handle_connection(int fd);

  std::string socket_path_;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::mutex mu_;
  std::condition_variable conn_cv_;
  int active_conns_ = 0;  // detached connection threads still running
  std::map<std::string, UnaryHandler> unary_;
  std::map<std::string, StreamHandler> streams_;
  std::set<int> conn_fds_;  // live connections, shut down on stop()
  bool stopping_ = false;
};

struct UnaryResult {
  int grpc_status = kUnknown;
  std::string message;   // grpc-message on failure
  std::string response;  // decoded message bytes on success
  bool transport_ok = false;
};

// One connection per call; ample for the single Register round-trip.
UnaryResult grpc_unary_call(const std::string& socket_path,
                            const std::string& rpc_path,
                            const std::string& request, int timeout_ms = 5000);

}  // namespace k3stpu::h2
