// TPU chip discovery from sysfs/devfs — C++ twin of k3stpu/utils/chips.py.
//
// The reference's device plugin enumerates GPUs through NVML (SURVEY.md §2b
// #9); on a TPU host the equivalent ground truth is PCI functions with
// Google's vendor id 0x1ae0 plus /dev/accel* (or vfio) device nodes. Both the
// OCI runtime shim and the device plugin link this. All lookups honor a root
// override (K3STPU_HOST_ROOT) so tests run against a fake tree (SURVEY.md §4).

#pragma once

#include <string>
#include <vector>

namespace k3stpu {

struct TpuChip {
  int index = 0;                       // stable order: sorted PCI BDF
  std::string pci_address;             // "0000:00:05.0"
  std::string device_id;               // "0x0062"
  std::string generation;              // "tpu-v5e" | "tpu-unknown" | ...
  int numa_node = -1;
  std::vector<std::string> dev_paths;  // e.g. {"/dev/accel0"}
  // ICI mesh coordinates on the host tray. Ground truth when the driver
  // (or site provisioning) exposes a per-chip `tpu_coords` sysfs attribute
  // ("x,y"); otherwise derived row-major from the tray shape — v5e host
  // trays are wired row-major, so (index % cols, index / cols).
  int coord_x = -1;
  int coord_y = -1;
  // Live telemetry (the reference's nvidia-smi shows memory + utilization,
  // reference README.md:78-84). -1 == unavailable, rendered "n/a". Sources,
  // best first: per-chip sysfs attributes if the driver exposes them
  // (tpu_mem_used_bytes / tpu_mem_total_bytes / tpu_duty_cycle_pct), then
  // the workload-exported metrics drop file (see kMetricsDropPath), then —
  // for the total only — the generation's known HBM size.
  long long mem_total_bytes = -1;
  long long mem_used_bytes = -1;
  int duty_cycle_pct = -1;
  // True when mem_used_bytes came from client-side accounting (the drop
  // file's source == "live_arrays" — the writer's own live-array sum, an
  // honest lower bound used when PJRT memory_stats() is empty). Rendered
  // with a '~' prefix so the reader knows it is an estimate, not
  // allocator truth.
  bool mem_estimated = false;
};

inline constexpr const char* kGoogleVendorId = "0x1ae0";
inline constexpr const char* kHostRootEnv = "K3STPU_HOST_ROOT";
// Where TPU workloads export live device metrics for host tools (written by
// k3stpu/utils/telemetry.py from jax memory_stats; the host CLI merges it
// into its table the way nvidia-smi merges NVML live data).
inline constexpr const char* kMetricsDropPath = "/run/k3stpu/metrics.json";

// Root directory of the host filesystem ("/" unless K3STPU_HOST_ROOT is set
// or an explicit override is given).
std::string host_root(const std::string& override_root = "");

// Scans {root}/sys/bus/pci/devices for Google TPU functions and matches them
// to device nodes. Returns chips ordered by PCI address.
std::vector<TpuChip> enumerate_chips(const std::string& root = "");

// Host path of libtpu.so under root, or "" when absent.
std::string find_libtpu(const std::string& root = "");

// "1x1", "2x2", "2x4" ... best-effort local ICI topology for n chips.
std::string topology_for(size_t n_chips);

// Columns of the host tray mesh for n chips (rows = n / cols): the x extent
// of the row-major coordinate assignment. 8 -> 4 (a 2x4 tray), 4 -> 2.
int tray_cols(size_t n_chips);

// TensorCores per chip for a generation string ("tpu-v5p" -> 2): v2/v3/v4/
// v5p chips carry two TensorCores (megacore), v5e/v6e one. The per-core
// sharing granularity (the reference's MIG-analogue knob) splits on this.
int cores_per_chip(const std::string& generation);

// HBM capacity per chip for a generation (public figures); -1 if unknown.
long long hbm_bytes_for(const std::string& generation);

// Merge live telemetry into `chips`: per-chip sysfs attributes win, then the
// workload-exported metrics drop file {root}{kMetricsDropPath}, then the
// generation HBM table fills mem_total. Missing data stays -1 ("n/a").
void fill_telemetry(std::vector<TpuChip>& chips, const std::string& root = "");

}  // namespace k3stpu
