file(REMOVE_RECURSE
  "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/main.cpp.o"
  "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/main.cpp.o.d"
  "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/spec_patch.cpp.o"
  "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/spec_patch.cpp.o.d"
  "tpu-container-runtime"
  "tpu-container-runtime.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/tpu-container-runtime.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
