# Empty compiler generated dependencies file for tpu-container-runtime.
# This may be replaced when dependencies are built.
