
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/native/tpu-container-runtime/main.cpp" "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/main.cpp.o" "gcc" "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/main.cpp.o.d"
  "/root/repo/native/tpu-container-runtime/spec_patch.cpp" "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/spec_patch.cpp.o" "gcc" "CMakeFiles/tpu-container-runtime.dir/tpu-container-runtime/spec_patch.cpp.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  "/root/repo/native/build-asan/CMakeFiles/k3stpu_common.dir/DependInfo.cmake"
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
