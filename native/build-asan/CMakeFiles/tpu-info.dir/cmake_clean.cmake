file(REMOVE_RECURSE
  "CMakeFiles/tpu-info.dir/tpu-info/main.cpp.o"
  "CMakeFiles/tpu-info.dir/tpu-info/main.cpp.o.d"
  "tpu-info"
  "tpu-info.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/tpu-info.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
