# Empty compiler generated dependencies file for tpu-info.
# This may be replaced when dependencies are built.
