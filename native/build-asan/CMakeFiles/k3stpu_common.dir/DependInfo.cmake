
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/native/common/chips.cpp" "CMakeFiles/k3stpu_common.dir/common/chips.cpp.o" "gcc" "CMakeFiles/k3stpu_common.dir/common/chips.cpp.o.d"
  "/root/repo/native/common/json.cpp" "CMakeFiles/k3stpu_common.dir/common/json.cpp.o" "gcc" "CMakeFiles/k3stpu_common.dir/common/json.cpp.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
