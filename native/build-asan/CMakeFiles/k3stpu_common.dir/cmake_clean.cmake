file(REMOVE_RECURSE
  "CMakeFiles/k3stpu_common.dir/common/chips.cpp.o"
  "CMakeFiles/k3stpu_common.dir/common/chips.cpp.o.d"
  "CMakeFiles/k3stpu_common.dir/common/json.cpp.o"
  "CMakeFiles/k3stpu_common.dir/common/json.cpp.o.d"
  "libk3stpu_common.a"
  "libk3stpu_common.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/k3stpu_common.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
