# Empty compiler generated dependencies file for k3stpu_common.
# This may be replaced when dependencies are built.
