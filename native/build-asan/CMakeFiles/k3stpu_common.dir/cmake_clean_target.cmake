file(REMOVE_RECURSE
  "libk3stpu_common.a"
)
