# Empty compiler generated dependencies file for tpu-device-plugin.
# This may be replaced when dependencies are built.
