file(REMOVE_RECURSE
  "CMakeFiles/tpu-device-plugin.dir/tpu-device-plugin/main.cpp.o"
  "CMakeFiles/tpu-device-plugin.dir/tpu-device-plugin/main.cpp.o.d"
  "CMakeFiles/tpu-device-plugin.dir/tpu-device-plugin/plugin.cpp.o"
  "CMakeFiles/tpu-device-plugin.dir/tpu-device-plugin/plugin.cpp.o.d"
  "tpu-device-plugin"
  "tpu-device-plugin.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/tpu-device-plugin.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
