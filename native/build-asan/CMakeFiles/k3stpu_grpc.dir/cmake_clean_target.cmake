file(REMOVE_RECURSE
  "libk3stpu_grpc.a"
)
