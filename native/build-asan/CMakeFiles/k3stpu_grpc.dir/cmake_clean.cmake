file(REMOVE_RECURSE
  "CMakeFiles/k3stpu_grpc.dir/common/grpc_transport.cpp.o"
  "CMakeFiles/k3stpu_grpc.dir/common/grpc_transport.cpp.o.d"
  "CMakeFiles/k3stpu_grpc.dir/common/hpack.cpp.o"
  "CMakeFiles/k3stpu_grpc.dir/common/hpack.cpp.o.d"
  "libk3stpu_grpc.a"
  "libk3stpu_grpc.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/k3stpu_grpc.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
