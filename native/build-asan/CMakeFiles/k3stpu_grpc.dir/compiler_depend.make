# Empty compiler generated dependencies file for k3stpu_grpc.
# This may be replaced when dependencies are built.
