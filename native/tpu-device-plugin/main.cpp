// tpu-device-plugin daemon: the DaemonSet binary.
//
// Lifecycle parity with the reference's plugin rollout (SURVEY.md §3.2):
// enumerate chips -> bind plugin socket -> Register with kubelet ->
// ListAndWatch streams chips x replicas device IDs -> Allocate returns
// devices/mounts/envs. `--replicas` is the time-slicing knob (reference
// values.yaml:18); `--dump` prints the enumerated inventory and exits
// (nvidia-smi-style check, reference README.md:71-93).

#include <atomic>
#include <csignal>
#include <cstring>
#include <iostream>
#include <string>

#include <unistd.h>

#include "../common/json.hpp"
#include "plugin.hpp"

namespace {

std::atomic<bool> g_stop{false};

void on_signal(int) { g_stop = true; }

int dump_inventory(const k3stpu::plugin::PluginConfig& config) {
  using k3stpu::json::Value;
  auto chips = k3stpu::enumerate_chips(config.host_root);
  auto root = Value::make_object();
  root->set("resource", Value::make_string(config.resource_name));
  root->set("replicas", Value::make_int(config.replicas));
  root->set("chip_count", Value::make_int(static_cast<int64_t>(chips.size())));
  root->set("granularity", Value::make_string(config.granularity));
  int64_t units = 0;
  for (const auto& c : chips)
    units += config.granularity == "core"
                 ? k3stpu::cores_per_chip(c.generation) : 1;
  root->set("schedulable", Value::make_int(units * config.replicas));
  root->set("topology", Value::make_string(k3stpu::topology_for(chips.size())));
  auto arr = root->ensure_array("chips");
  for (const auto& c : chips) {
    auto o = Value::make_object();
    o->set("index", Value::make_int(c.index));
    o->set("pci", Value::make_string(c.pci_address));
    o->set("generation", Value::make_string(c.generation));
    o->set("numa", Value::make_int(c.numa_node));
    auto coords = o->ensure_array("coords");
    coords->arr_v.push_back(Value::make_int(c.coord_x));
    coords->arr_v.push_back(Value::make_int(c.coord_y));
    auto devs = o->ensure_array("dev_paths");
    for (const auto& d : c.dev_paths)
      devs->arr_v.push_back(Value::make_string(d));
    arr->arr_v.push_back(o);
  }
  std::cout << k3stpu::json::dump(root);
  return 0;
}

void usage() {
  std::cerr <<
      "tpu-device-plugin [options]\n"
      "  --resource NAME       extended resource name (google.com/tpu)\n"
      "  --replicas N          shares per chip, parity with time-slicing\n"
      "  --granularity G       chip (default) | core (per-TensorCore units)\n"
      "  --fail-multi          reject >1 device per container\n"
      "  --plugin-dir DIR      kubelet device-plugin dir\n"
      "  --socket NAME         plugin socket filename (k3stpu.sock)\n"
      "  --host-root DIR       fake host root (tests)\n"
      "  --scan-seconds N      health rescan interval\n"
      "  --no-register         serve without registering (tests)\n"
      "  --dump                print chip inventory JSON and exit\n";
}

}  // namespace

int main(int argc, char** argv) {
  k3stpu::plugin::PluginConfig config;
  bool dump = false, no_register = false;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&](const char* flag) -> std::string {
      if (i + 1 >= argc) {
        std::cerr << flag << " requires a value\n";
        std::exit(2);
      }
      return argv[++i];
    };
    if (a == "--resource") config.resource_name = next("--resource");
    else if (a == "--replicas") config.replicas = std::stoi(next("--replicas"));
    else if (a == "--granularity") config.granularity = next("--granularity");
    else if (a == "--fail-multi") config.fail_requests_greater_than_one = true;
    else if (a == "--plugin-dir") config.device_plugin_dir = next("--plugin-dir");
    else if (a == "--socket") config.socket_name = next("--socket");
    else if (a == "--host-root") config.host_root = next("--host-root");
    else if (a == "--scan-seconds")
      config.health_scan_seconds = std::stoi(next("--scan-seconds"));
    else if (a == "--no-register") no_register = true;
    else if (a == "--dump") dump = true;
    else if (a == "--help" || a == "-h") { usage(); return 0; }
    else { std::cerr << "unknown option " << a << "\n"; usage(); return 2; }
  }
  if (config.replicas < 1) {
    std::cerr << "--replicas must be >= 1\n";
    return 2;
  }
  if (config.granularity != "chip" && config.granularity != "core") {
    std::cerr << "--granularity must be chip or core\n";
    return 2;
  }
  if (dump) return dump_inventory(config);

  const std::string kubelet_socket =
      config.device_plugin_dir + "/kubelet.sock";

  std::signal(SIGINT, on_signal);
  std::signal(SIGTERM, on_signal);
  // Kubelet restarts close our sockets mid-write; that must surface as a
  // send() error (re-register path), never a fatal SIGPIPE.
  std::signal(SIGPIPE, SIG_IGN);

  // Outer loop = kubelet-restart recovery: when kubelet restarts it wipes
  // /var/lib/kubelet/device-plugins/ (taking our socket with it) and expects
  // plugins to re-register — otherwise google.com/tpu silently drops to 0
  // until the DaemonSet pod restarts. Rebind + re-register whenever our
  // socket vanishes; retry with backoff when kubelet is not up yet.
  bool first = true;
  while (!g_stop) {
    k3stpu::plugin::TpuDevicePlugin plugin(config);
    if (first) {
      auto chips = plugin.chips_snapshot();
      std::cerr << "tpu-device-plugin: " << chips.size() << " chip(s), "
                << config.replicas << " replica(s) -> "
                << chips.size() * config.replicas << " schedulable "
                << config.resource_name << " on " << plugin.socket_path()
                << "\n";
      first = false;
    }
    if (!plugin.serve(kubelet_socket, no_register)) {
      for (int i = 0; i < 10 && !g_stop; ++i) ::usleep(200 * 1000);
      continue;
    }
    while (!g_stop &&
           ::access(plugin.socket_path().c_str(), F_OK) == 0)
      ::usleep(200 * 1000);
    plugin.stop();
    if (!g_stop)
      std::cerr << "tpu-device-plugin: socket removed (kubelet restart?); "
                   "re-registering\n";
  }
  return 0;
}
