// TPU device plugin: advertises google.com/tpu to kubelet with N-way
// per-chip sharing and topology-aware allocation.
//
// TPU-native rebuild of the reference's NVIDIA device plugin + its
// time-slicing policy (SURVEY.md §2b #9): `replicas` here mirrors
// `timeSlicing.resources[].replicas: 4` (reference values.yaml:12-18,
// README.md:112 — "treat that one GPU as if it were actually four");
// `fail_requests_greater_than_one` mirrors values.yaml:15. Device IDs are
// "tpu-<chip>-<replica>" so kubelet counts chips x replicas schedulable
// units while Allocate collapses them back to physical chips.
//
// The message-level handlers are pure bytes-in/bytes-out functions over the
// hand-rolled wire layer, so tests drive them without sockets, and the gRPC
// server (grpc_transport) binds them to the kubelet protocol.

#pragma once

#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "../common/chips.hpp"
#include "../common/grpc_transport.hpp"

namespace k3stpu::plugin {

struct PluginConfig {
  std::string resource_name = "google.com/tpu";
  int replicas = 1;  // shares per physical chip/core (1 = exclusive)
  bool fail_requests_greater_than_one = false;
  std::string device_plugin_dir = "/var/lib/kubelet/device-plugins";
  std::string socket_name = "k3stpu.sock";
  std::string host_root;  // "" = real /
  int health_scan_seconds = 5;
  // "chip": one schedulable unit per chip (x replicas). "core": one per
  // TensorCore (the reference's MIG-analogue spatial split, SURVEY.md §2c)
  // — on 2-core generations (v2-v4, v5p) a chip becomes 2 units.
  std::string granularity = "chip";
};

struct DeviceId {
  int chip = 0;
  int core = -1;  // -1 = whole chip (chip-granularity id)
  int replica = 0;
};

// "tpu-<chip>-<replica>" (chip granularity) or "tpu-<chip>-c<core>-<replica>"
// (core granularity); returns false on malformed input.
bool parse_device_id(const std::string& id, DeviceId& out);
std::string format_device_id(int chip, int replica);
std::string format_device_id(int chip, int core, int replica);

class TpuDevicePlugin {
 public:
  explicit TpuDevicePlugin(PluginConfig config);

  // -- protobuf message handlers (testable without any socket) --
  std::string handle_options(const std::string& request) const;
  std::string list_and_watch_payload();  // current ListAndWatchResponse
  std::string handle_allocate(const std::string& request);
  std::string handle_preferred(const std::string& request);
  std::string handle_prestart(const std::string& request) const;

  // Re-enumerates chips; wakes ListAndWatch streams when inventory changed.
  void rescan();

  // Serving: binds the plugin socket, registers with kubelet, runs the
  // health-rescan loop until stop(). Returns false if bind/register fails.
  bool serve(const std::string& kubelet_socket, bool skip_register = false);
  void stop();

  std::string socket_path() const {
    return config_.device_plugin_dir + "/" + config_.socket_name;
  }
  const PluginConfig& config() const { return config_; }
  std::vector<TpuChip> chips_snapshot();

  // Builds the RegisterRequest this plugin sends to kubelet.
  std::string register_request() const;

 private:
  std::string allocate_one_container(const std::vector<std::string>& ids);

  PluginConfig config_;
  h2::GrpcServer server_;
  std::thread scan_thread_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<TpuChip> chips_;
  uint64_t state_version_ = 0;
  bool stopping_ = false;
};

}  // namespace k3stpu::plugin
