#include "plugin.hpp"

#include <algorithm>
#include <chrono>
#include <iostream>
#include <map>

#include "../common/protowire.hpp"

namespace k3stpu::plugin {

namespace {

using pw::Reader;

constexpr const char* kHealthy = "Healthy";

// v1beta1.Device message.
std::string encode_device(const std::string& id, const std::string& health,
                          int numa_node) {
  std::string dev;
  pw::put_string(dev, 1, id);
  pw::put_string(dev, 2, health);
  if (numa_node >= 0) {
    std::string numa;
    pw::put_uint(numa, 1, static_cast<uint64_t>(numa_node));
    std::string topo;
    pw::put_message(topo, 1, numa);
    pw::put_message(dev, 3, topo);
  }
  return dev;
}

std::vector<std::string> parse_string_list(const std::string& msg,
                                           uint32_t field) {
  std::vector<std::string> out;
  Reader r(msg);
  uint32_t f;
  pw::WireType wt;
  while (r.next(f, wt)) {
    if (f == field && wt == pw::kLenDelim) {
      std::string s;
      if (!r.bytes(s)) break;
      out.push_back(std::move(s));
    } else if (!r.skip(wt)) {
      break;
    }
  }
  return out;
}

std::string csv(const std::vector<int>& xs) {
  std::string out;
  for (size_t i = 0; i < xs.size(); ++i)
    out += (i ? "," : "") + std::to_string(xs[i]);
  return out;
}

}  // namespace

bool parse_device_id(const std::string& id, DeviceId& out) {
  if (id.rfind("tpu-", 0) != 0) return false;
  size_t dash = id.find('-', 4);
  if (dash == std::string::npos) return false;
  try {
    out.chip = std::stoi(id.substr(4, dash - 4));
    std::string rest = id.substr(dash + 1);
    out.core = -1;
    if (!rest.empty() && rest[0] == 'c') {  // "c<core>-<replica>"
      size_t d2 = rest.find('-');
      if (d2 == std::string::npos) return false;
      out.core = std::stoi(rest.substr(1, d2 - 1));
      rest = rest.substr(d2 + 1);
      if (out.core < 0) return false;
    }
    out.replica = std::stoi(rest);
  } catch (...) {
    return false;
  }
  return out.chip >= 0 && out.replica >= 0;
}

std::string format_device_id(int chip, int replica) {
  return "tpu-" + std::to_string(chip) + "-" + std::to_string(replica);
}

std::string format_device_id(int chip, int core, int replica) {
  return "tpu-" + std::to_string(chip) + "-c" + std::to_string(core) + "-" +
         std::to_string(replica);
}

TpuDevicePlugin::TpuDevicePlugin(PluginConfig config)
    : config_(std::move(config)) {
  chips_ = enumerate_chips(config_.host_root);
}

std::vector<TpuChip> TpuDevicePlugin::chips_snapshot() {
  std::lock_guard<std::mutex> lock(mu_);
  return chips_;
}

std::string TpuDevicePlugin::handle_options(const std::string&) const {
  std::string out;
  pw::put_bool(out, 1, false);  // pre_start_required
  pw::put_bool(out, 2, true);   // get_preferred_allocation_available
  return out;
}

std::string TpuDevicePlugin::list_and_watch_payload() {
  std::lock_guard<std::mutex> lock(mu_);
  std::string out;
  const bool per_core = config_.granularity == "core";
  for (const auto& chip : chips_) {
    const std::string health =
        chip.dev_paths.empty() ? "Unhealthy" : kHealthy;
    const int cores = per_core ? cores_per_chip(chip.generation) : 1;
    for (int c = 0; c < cores; ++c)
      for (int r = 0; r < config_.replicas; ++r)
        pw::put_message(
            out, 1,
            encode_device(per_core ? format_device_id(chip.index, c, r)
                                   : format_device_id(chip.index, r),
                          health, chip.numa_node));
  }
  return out;
}

std::string TpuDevicePlugin::allocate_one_container(
    const std::vector<std::string>& ids) {
  if (config_.fail_requests_greater_than_one && ids.size() > 1)
    throw h2::GrpcError{3 /*INVALID_ARGUMENT*/,
                        "requests for more than one " + config_.resource_name +
                            " are disabled (failRequestsGreaterThanOne)"};

  std::set<int> chip_set;
  std::map<int, std::set<int>> cores_by_chip;  // core-granularity ids only
  for (const auto& id : ids) {
    DeviceId d;
    if (!parse_device_id(id, d))
      throw h2::GrpcError{3, "malformed device id: " + id};
    chip_set.insert(d.chip);
    if (d.core >= 0) cores_by_chip[d.chip].insert(d.core);
  }

  std::lock_guard<std::mutex> lock(mu_);
  std::map<int, const TpuChip*> by_index;
  for (const auto& c : chips_) by_index[c.index] = &c;

  std::vector<int> chip_list(chip_set.begin(), chip_set.end());
  std::string resp;

  // envs (map<string,string> = repeated entry messages, field 1)
  auto put_env = [&resp](const std::string& k, const std::string& v) {
    pw::put_message(resp, 1, pw::map_entry(k, v));
  };
  put_env("TPU_VISIBLE_CHIPS", csv(chip_list));
  put_env("TPU_CHIPS_PER_PROCESS_BOUNDS",
          "1,1," + std::to_string(chip_list.size()));
  put_env("TPU_PROCESS_BOUNDS", "1,1,1");
  if (!chips_.empty())
    put_env("TPU_ACCELERATOR_TYPE",
            chips_.front().generation + "-" + std::to_string(chip_list.size()));

  // Per-core (MIG-analogue) allocations: tell the pod which TensorCores of
  // its visible chips it owns ("chip:core" csv, consumed by the workload
  // launcher to pin XLA to a core), and derive its HBM share from the
  // fraction of the chip it holds.
  double min_core_share = 1.0;
  if (config_.granularity == "core" && !cores_by_chip.empty()) {
    std::string vis;
    for (const auto& [chip, cores] : cores_by_chip) {
      auto it = by_index.find(chip);
      const int n_cores =
          it != by_index.end() ? cores_per_chip(it->second->generation) : 1;
      min_core_share = std::min(
          min_core_share, double(cores.size()) / std::max(n_cores, 1));
      for (int c : cores)
        vis += (vis.empty() ? "" : ",") + std::to_string(chip) + ":" +
               std::to_string(c);
    }
    put_env("TPU_VISIBLE_TENSORCORES", vis);
  }

  const double share = min_core_share / config_.replicas;
  if (share < 1.0) {
    // Shared chips (replica time-slicing and/or per-core split): multiple
    // JAX processes coexist on one chip, so cap each pod's premapped HBM
    // slice instead of letting libtpu assume exclusive ownership
    // (SURVEY.md §7 "Hard parts": Allocate semantics for shared chips).
    put_env("TPU_MEM_FRACTION", std::to_string(share).substr(0, 6));
    put_env("TPU_ALLOW_MULTIPLE_LIBTPU_PROCESSES", "1");
  }

  // device nodes + libtpu mount
  bool vfio_ctl = false;
  for (int chip : chip_list) {
    auto it = by_index.find(chip);
    if (it == by_index.end())
      throw h2::GrpcError{5 /*NOT_FOUND*/,
                          "unknown chip " + std::to_string(chip)};
    for (const auto& dev : it->second->dev_paths) {
      if (dev == "/dev/vfio/vfio") {
        vfio_ctl = true;
        continue;
      }
      std::string spec;
      pw::put_string(spec, 1, dev);  // container_path
      pw::put_string(spec, 2, dev);  // host_path
      pw::put_string(spec, 3, "rwm");
      pw::put_message(resp, 3, spec);
    }
  }
  if (vfio_ctl) {
    std::string spec;
    pw::put_string(spec, 1, "/dev/vfio/vfio");
    pw::put_string(spec, 2, "/dev/vfio/vfio");
    pw::put_string(spec, 3, "rwm");
    pw::put_message(resp, 3, spec);
  }

  const std::string libtpu = find_libtpu(config_.host_root);
  if (!libtpu.empty()) {
    std::string mount;
    pw::put_string(mount, 1, "/lib/libtpu.so");
    pw::put_string(mount, 2, libtpu);
    pw::put_bool(mount, 3, true);
    pw::put_message(resp, 2, mount);
  }

  pw::put_message(resp, 4,
                  pw::map_entry("tpu.google.com/chips", csv(chip_list)));
  return resp;
}

std::string TpuDevicePlugin::handle_allocate(const std::string& request) {
  // AllocateRequest{ repeated ContainerAllocateRequest{ devicesIDs=1 } = 1 }
  std::string out;
  Reader r(request);
  uint32_t f;
  pw::WireType wt;
  while (r.next(f, wt)) {
    if (f == 1 && wt == pw::kLenDelim) {
      std::string creq;
      if (!r.bytes(creq)) break;
      pw::put_message(out, 1,
                      allocate_one_container(parse_string_list(creq, 1)));
    } else if (!r.skip(wt)) {
      break;
    }
  }
  return out;
}

std::string TpuDevicePlugin::handle_preferred(const std::string& request) {
  std::string out;
  Reader r(request);
  uint32_t f;
  pw::WireType wt;
  while (r.next(f, wt)) {
    if (!(f == 1 && wt == pw::kLenDelim)) {
      if (!r.skip(wt)) break;
      continue;
    }
    std::string creq;
    if (!r.bytes(creq)) break;

    std::vector<std::string> available = parse_string_list(creq, 1);
    std::vector<std::string> must = parse_string_list(creq, 2);
    int64_t size = 0;
    {
      Reader cr(creq);
      uint32_t cf;
      pw::WireType cwt;
      while (cr.next(cf, cwt)) {
        if (cf == 3 && cwt == pw::kVarint) {
          uint64_t v;
          if (cr.varint(v)) size = static_cast<int64_t>(v);
        } else if (!cr.skip(cwt)) {
          break;
        }
      }
    }

    // Topology-aware choice (SURVEY.md §7 "Hard parts"): pick chips that
    // form the tightest axis-aligned rectangle in actual ICI coordinates
    // (TpuChip.coord_x/y — sysfs-exposed when available, row-major tray
    // defaults otherwise). Contiguous *indices* are NOT always neighbors:
    // on a 2x4 tray, chips 3 (3,0) and 4 (0,1) share no ICI link, while
    // {0,1,4,5} form a perfect 2x2 sub-mesh.
    std::map<int, std::vector<std::string>> by_chip;
    for (auto& id : available) {
      DeviceId d;
      if (parse_device_id(id, d)) by_chip[d.chip].push_back(id);
    }
    for (auto& [_, ids] : by_chip)
      std::sort(ids.begin(), ids.end());

    std::vector<std::string> chosen(must.begin(), must.end());
    std::set<std::string> chosen_set(must.begin(), must.end());
    for (auto& [_, ids] : by_chip) {  // must-ids no longer count as free
      ids.erase(std::remove_if(ids.begin(), ids.end(),
                               [&](const std::string& id) {
                                 return chosen_set.count(id) > 0;
                               }),
                ids.end());
    }

    struct ChipPos { int chip; int x; int y; size_t free; };
    std::vector<ChipPos> pos;
    std::set<int> must_chips;
    for (const auto& id : must) {
      DeviceId d;
      if (parse_device_id(id, d)) must_chips.insert(d.chip);
    }
    std::vector<std::pair<int, int>> must_pos;  // coords of pinned chips
    {
      std::lock_guard<std::mutex> lock(mu_);
      for (const auto& c : chips_) {
        if (c.coord_x < 0 || c.coord_y < 0) continue;
        auto it = by_chip.find(c.index);
        if (it != by_chip.end())
          pos.push_back({c.index, c.coord_x, c.coord_y, it->second.size()});
        if (must_chips.count(c.index))
          must_pos.emplace_back(c.coord_x, c.coord_y);
      }
    }

    const size_t need =
        size > static_cast<int64_t>(chosen.size())
            ? static_cast<size_t>(size) - chosen.size() : 0;

    // Enumerate all rectangles over the tray; among those whose available
    // capacity covers the request, minimize (area, perimeter) — the most
    // compact connected sub-mesh — tie-broken toward the origin for
    // determinism.
    //
    // Scale bound: a device plugin sees ONE host's chips — 4-8 on every
    // shipping tray (v5e 2x4, v5p 2x2x1 per host), 16 for a hypothetical
    // 4x4. The enumeration is O((max_x*max_y)^2) rectangles with the
    // area early-out below cutting the per-rectangle capacity scan to
    // strictly-better candidates: ~100 rectangles on 2x4, ~3k on 8x8 —
    // microseconds either way. Pod-slice-scale topology (16x16+) is the
    // SCHEDULER's job across nodes, never this per-node search; if a
    // future accelerator puts hundreds of chips on one host, switch to
    // growing rectangles from each must-anchor instead.
    int max_x = 0, max_y = 0;
    for (const auto& p : pos) {
      max_x = std::max(max_x, p.x);
      max_y = std::max(max_y, p.y);
    }
    for (const auto& [x, y] : must_pos) {
      max_x = std::max(max_x, x);
      max_y = std::max(max_y, y);
    }
    struct Rect { int x0, y0, x1, y1; };
    Rect best{};
    long best_area = -1, best_perim = 0;
    if (need > 0 && !pos.empty()) {
      for (int y0 = 0; y0 <= max_y; ++y0)
        for (int y1 = y0; y1 <= max_y; ++y1)
          for (int x0 = 0; x0 <= max_x; ++x0)
            for (int x1 = x0; x1 <= max_x; ++x1) {
              // Pinned (must-include) chips anchor the rectangle: the
              // extra chips must form one sub-mesh WITH them, not a
              // compact island somewhere else on the tray.
              bool covers_must = true;
              for (const auto& [mx, my] : must_pos)
                if (mx < x0 || mx > x1 || my < y0 || my > y1) {
                  covers_must = false;
                  break;
                }
              if (!covers_must) continue;
              // Early-out BEFORE the O(|pos|) capacity scan: a rectangle
              // that cannot beat the incumbent on (area, perimeter) need
              // not be costed at all.
              long area = long(x1 - x0 + 1) * (y1 - y0 + 1);
              long perim = long(x1 - x0 + 1) + (y1 - y0 + 1);
              if (best_area >= 0 &&
                  (area > best_area ||
                   (area == best_area && perim >= best_perim)))
                continue;
              size_t cap = 0;
              for (const auto& p : pos)
                if (p.x >= x0 && p.x <= x1 && p.y >= y0 && p.y <= y1)
                  cap += p.free;
              if (cap < need) continue;
              best = {x0, y0, x1, y1};
              best_area = area;
              best_perim = perim;
            }
    }
    if (best_area >= 0) {
      // Fill row-major within the winning rectangle.
      std::sort(pos.begin(), pos.end(), [](const ChipPos& a, const ChipPos& b) {
        return a.y != b.y ? a.y < b.y : a.x < b.x;
      });
      for (const auto& p : pos) {
        if (chosen.size() >= static_cast<size_t>(size)) break;
        if (p.x < best.x0 || p.x > best.x1 || p.y < best.y0 || p.y > best.y1)
          continue;
        for (const auto& id : by_chip[p.chip]) {
          if (chosen.size() >= static_cast<size_t>(size)) break;
          if (chosen_set.insert(id).second) chosen.push_back(id);
        }
      }
    }
    // Fall back to any available ids if the rectangle search came up short
    // (e.g. ids for chips that vanished from inventory).
    for (const auto& id : available) {
      if (chosen.size() >= static_cast<size_t>(size)) break;
      if (chosen_set.insert(id).second) chosen.push_back(id);
    }

    std::string cresp;
    for (const auto& id : chosen) pw::put_string(cresp, 1, id);
    pw::put_message(out, 1, cresp);
  }
  return out;
}

std::string TpuDevicePlugin::handle_prestart(const std::string&) const {
  return "";  // PreStartContainerResponse{}
}

void TpuDevicePlugin::rescan() {
  auto fresh = enumerate_chips(config_.host_root);
  std::lock_guard<std::mutex> lock(mu_);
  bool changed = fresh.size() != chips_.size();
  if (!changed) {
    for (size_t i = 0; i < fresh.size(); ++i) {
      if (fresh[i].pci_address != chips_[i].pci_address ||
          fresh[i].dev_paths != chips_[i].dev_paths) {
        changed = true;
        break;
      }
    }
  }
  if (changed) {
    chips_ = std::move(fresh);
    ++state_version_;
    cv_.notify_all();
  }
}

std::string TpuDevicePlugin::register_request() const {
  std::string opts;
  pw::put_bool(opts, 1, false);
  pw::put_bool(opts, 2, true);
  std::string req;
  pw::put_string(req, 1, "v1beta1");
  pw::put_string(req, 2, config_.socket_name);
  pw::put_string(req, 3, config_.resource_name);
  pw::put_message(req, 4, opts);
  return req;
}

bool TpuDevicePlugin::serve(const std::string& kubelet_socket,
                            bool skip_register) {
  server_.add_unary("/v1beta1.DevicePlugin/GetDevicePluginOptions",
                    [this](const std::string& req) {
                      return handle_options(req);
                    });
  server_.add_unary("/v1beta1.DevicePlugin/Allocate",
                    [this](const std::string& req) {
                      return handle_allocate(req);
                    });
  server_.add_unary("/v1beta1.DevicePlugin/GetPreferredAllocation",
                    [this](const std::string& req) {
                      return handle_preferred(req);
                    });
  server_.add_unary("/v1beta1.DevicePlugin/PreStartContainer",
                    [this](const std::string& req) {
                      return handle_prestart(req);
                    });
  server_.add_server_stream(
      "/v1beta1.DevicePlugin/ListAndWatch",
      [this](const std::string&, const h2::StreamCtx& ctx) {
        // The reference stack's hot loop (SURVEY.md §3.2): stream the device
        // list, then again on every inventory change, until the client goes
        // away or the plugin stops. The wait polls ctx.alive() so a kubelet
        // reconnect doesn't strand this thread until the next (possibly
        // never) inventory change.
        uint64_t seen;
        {
          std::lock_guard<std::mutex> lock(mu_);
          seen = state_version_;
        }
        if (!ctx.write(list_and_watch_payload())) return;
        for (;;) {
          bool changed;
          {
            std::unique_lock<std::mutex> lock(mu_);
            cv_.wait_for(lock, std::chrono::milliseconds(500), [&] {
              return stopping_ || state_version_ != seen;
            });
            if (stopping_) return;
            changed = state_version_ != seen;
            seen = state_version_;
          }
          if (!ctx.alive()) return;
          if (changed && !ctx.write(list_and_watch_payload())) return;
        }
      });

  if (!server_.start(socket_path())) {
    std::cerr << "tpu-device-plugin: cannot bind " << socket_path() << "\n";
    return false;
  }

  if (!skip_register) {
    auto result = h2::grpc_unary_call(
        kubelet_socket, "/v1beta1.Registration/Register", register_request());
    if (!result.transport_ok || result.grpc_status != h2::kOk) {
      std::cerr << "tpu-device-plugin: Register failed (transport="
                << result.transport_ok << " status=" << result.grpc_status
                << " message=\"" << result.message << "\")\n";
      server_.stop();
      return false;
    }
  }

  scan_thread_ = std::thread([this] {
    for (;;) {
      {
        std::unique_lock<std::mutex> lock(mu_);
        if (cv_.wait_for(lock,
                         std::chrono::seconds(config_.health_scan_seconds),
                         [this] { return stopping_; }))
          return;
      }
      rescan();
    }
  });
  return true;
}

void TpuDevicePlugin::stop() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (stopping_) return;
    stopping_ = true;
    cv_.notify_all();
  }
  if (scan_thread_.joinable()) scan_thread_.join();
  server_.stop();
}

}  // namespace k3stpu::plugin
