#include "spec_patch.hpp"

#include <algorithm>
#include <sstream>

#include <sys/stat.h>
#include <sys/sysmacros.h>

namespace k3stpu::runtime {

namespace {

using json::Value;
using json::ValuePtr;

std::string env_lookup(const ValuePtr& spec, const std::string& name) {
  auto process = spec->get("process");
  if (!process) return "";
  auto env = process->get("env");
  if (!env || !env->is_array()) return "";
  const std::string prefix = name + "=";
  for (const auto& e : env->arr_v) {
    if (e->is_string() && e->str_v.rfind(prefix, 0) == 0)
      return e->str_v.substr(prefix.size());
  }
  return "";
}

bool env_present(const ValuePtr& spec, const std::string& name) {
  auto process = spec->get("process");
  if (!process) return false;
  auto env = process->get("env");
  if (!env || !env->is_array()) return false;
  const std::string prefix = name + "=";
  for (const auto& e : env->arr_v)
    if (e->is_string() && e->str_v.rfind(prefix, 0) == 0) return true;
  return false;
}

void add_env(const ValuePtr& spec, const std::string& name,
             const std::string& value, PatchResult& result) {
  if (env_present(spec, name)) return;
  auto process = spec->ensure_object("process");
  auto env = process->ensure_array("env");
  env->arr_v.push_back(Value::make_string(name + "=" + value));
  result.env_added.push_back(name);
}

bool has_mount(const ValuePtr& spec, const std::string& dest) {
  auto mounts = spec->get("mounts");
  if (!mounts || !mounts->is_array()) return false;
  for (const auto& m : mounts->arr_v) {
    auto d = m->get("destination");
    if (d && d->as_string() == dest) return true;
  }
  return false;
}

void add_bind_mount(const ValuePtr& spec, const std::string& src,
                    const std::string& dest, bool read_only,
                    PatchResult& result) {
  if (has_mount(spec, dest)) return;
  auto mounts = spec->ensure_array("mounts");
  auto m = Value::make_object();
  m->set("destination", Value::make_string(dest));
  m->set("type", Value::make_string("bind"));
  m->set("source", Value::make_string(src));
  auto opts = Value::make_array();
  opts->arr_v.push_back(Value::make_string("rbind"));
  opts->arr_v.push_back(Value::make_string(read_only ? "ro" : "rw"));
  opts->arr_v.push_back(Value::make_string("nosuid"));
  opts->arr_v.push_back(Value::make_string("nodev"));
  m->set("options", opts);
  mounts->arr_v.push_back(m);
  ++result.n_mounts;
}

bool has_device(const ValuePtr& linux_obj, const std::string& path) {
  auto devices = linux_obj->get("devices");
  if (!devices || !devices->is_array()) return false;
  for (const auto& d : devices->arr_v) {
    auto p = d->get("path");
    if (p && p->as_string() == path) return true;
  }
  return false;
}

// Adds the device node plus its cgroup allow-list entry.
void add_device(const ValuePtr& spec, const std::string& container_path,
                const std::string& host_path, PatchResult& result) {
  auto linux_obj = spec->ensure_object("linux");
  if (has_device(linux_obj, container_path)) return;

  struct stat st{};
  int64_t major = 0, minor = 0;
  std::string dev_type = "c";
  if (::stat(host_path.c_str(), &st) == 0 &&
      (S_ISCHR(st.st_mode) || S_ISBLK(st.st_mode))) {
    dev_type = S_ISBLK(st.st_mode) ? "b" : "c";
    major = static_cast<int64_t>(::major(st.st_rdev));
    minor = static_cast<int64_t>(::minor(st.st_rdev));
  }

  auto devices = linux_obj->ensure_array("devices");
  auto d = Value::make_object();
  d->set("path", Value::make_string(container_path));
  d->set("type", Value::make_string(dev_type));
  d->set("major", Value::make_int(major));
  d->set("minor", Value::make_int(minor));
  d->set("fileMode", Value::make_int(0666));
  d->set("uid", Value::make_int(0));
  d->set("gid", Value::make_int(0));
  devices->arr_v.push_back(d);

  auto resources = linux_obj->ensure_object("resources");
  auto allow = resources->ensure_array("devices");
  auto rule = Value::make_object();
  rule->set("allow", Value::make_bool(true));
  rule->set("type", Value::make_string(dev_type));
  rule->set("major", Value::make_int(major));
  rule->set("minor", Value::make_int(minor));
  rule->set("access", Value::make_string("rwm"));
  allow->arr_v.push_back(rule);
  ++result.n_devices;
}

std::vector<int> parse_visible(const std::string& csv, size_t n_chips) {
  std::vector<int> out;
  if (csv.empty() || csv == "all") {
    for (size_t i = 0; i < n_chips; ++i) out.push_back(static_cast<int>(i));
    return out;
  }
  std::stringstream ss(csv);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    try {
      int v = std::stoi(tok);
      if (v >= 0 && static_cast<size_t>(v) < n_chips) out.push_back(v);
    } catch (...) {
      // Ignore malformed entries; an empty result injects nothing, which
      // surfaces quickly in the probe pod rather than corrupting the spec.
    }
  }
  std::sort(out.begin(), out.end());
  out.erase(std::unique(out.begin(), out.end()), out.end());
  return out;
}

}  // namespace

bool wants_injection(const json::ValuePtr& spec) {
  if (env_present(spec, "TPU_VISIBLE_CHIPS")) return true;
  auto annotations = spec->get("annotations");
  if (annotations && annotations->is_object()) {
    auto a = annotations->get("tpu.google.com/inject");
    if (a && a->as_string() == "true") return true;
  }
  return false;
}

PatchResult patch_spec(json::ValuePtr spec, const PatchOptions& opts) {
  PatchResult result;
  if (!opts.always && !wants_injection(spec)) return result;
  result.injected = true;

  const std::string root = host_root(opts.host_root);
  auto chips = enumerate_chips(root);

  std::string visible = opts.visible_chips;
  if (visible.empty()) visible = env_lookup(spec, "TPU_VISIBLE_CHIPS");
  auto selected = parse_visible(visible, chips.size());

  const std::string host_prefix = (root == "/") ? "" : root;
  bool vfio_ctl = false;
  for (int idx : selected) {
    for (const auto& dev : chips[idx].dev_paths) {
      if (dev == "/dev/vfio/vfio") {
        vfio_ctl = true;
        continue;
      }
      add_device(spec, dev, host_prefix + dev, result);
    }
  }
  if (vfio_ctl) add_device(spec, "/dev/vfio/vfio",
                           host_prefix + "/dev/vfio/vfio", result);

  const std::string libtpu = find_libtpu(root);
  if (!libtpu.empty())
    add_bind_mount(spec, host_prefix + libtpu, "/lib/libtpu.so",
                   /*read_only=*/true, result);

  // Env contract consumed by libtpu/JAX inside the pod. TPU_VISIBLE_CHIPS is
  // normally already present (device plugin Allocate); fill the rest.
  if (!selected.empty()) {
    std::string csv;
    for (size_t i = 0; i < selected.size(); ++i)
      csv += (i ? "," : "") + std::to_string(selected[i]);
    add_env(spec, "TPU_VISIBLE_CHIPS", csv, result);
    add_env(spec, "TPU_CHIPS_PER_PROCESS_BOUNDS",
            "1,1," + std::to_string(selected.size()), result);
    add_env(spec, "TPU_PROCESS_BOUNDS", "1,1,1", result);
    if (!libtpu.empty())
      add_env(spec, "TPU_LIBRARY_PATH", "/lib/libtpu.so", result);
    if (!chips.empty())
      add_env(spec, "TPU_ACCELERATOR_TYPE",
              chips[0].generation + "-" + std::to_string(selected.size()),
              result);
  }
  return result;
}

}  // namespace k3stpu::runtime
