// OCI spec rewriting: inject libtpu + TPU device nodes into a container spec.
//
// TPU-native equivalent of what the reference's nvidia-container-runtime +
// libnvidia-container prestart hook do for GPU pods ("The nvidia runtime will
// automatically copy everything needed for your pod to use the GPU" —
// reference README.md:164; install at README.md:57-69). Instead of a prestart
// hook binary we rewrite config.json directly before delegating to runc:
// fewer moving parts and unit-testable as a pure JSON->JSON function
// (SURVEY.md §7 step 1: "Unit-testable by spec-diffing").

#pragma once

#include <string>
#include <vector>

#include "../common/chips.hpp"
#include "../common/json.hpp"

namespace k3stpu::runtime {

struct PatchOptions {
  // Inject even when the spec carries no TPU request marker.
  bool always = false;
  // Host root override for discovery (tests use a fake tree).
  std::string host_root;
  // When non-empty, overrides discovered chips (device-plugin pre-selected
  // visible chips, comma-separated indices from TPU_VISIBLE_CHIPS).
  std::string visible_chips;
};

struct PatchResult {
  bool injected = false;       // false: spec had no TPU request and !always
  int n_devices = 0;           // device nodes added
  int n_mounts = 0;            // bind mounts added
  std::vector<std::string> env_added;
};

// Returns true when the spec asks for TPU injection: an env var
// TPU_VISIBLE_CHIPS=... (set by the device plugin's Allocate response) or the
// pod annotation "tpu.google.com/inject" == "true". Mirrors how the NVIDIA
// runtime keys off NVIDIA_VISIBLE_DEVICES.
bool wants_injection(const json::ValuePtr& spec);

// Mutates the spec in place. Idempotent: running twice adds nothing new.
PatchResult patch_spec(json::ValuePtr spec, const PatchOptions& opts);

}  // namespace k3stpu::runtime
