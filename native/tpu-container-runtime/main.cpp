// tpu-container-runtime: OCI runtime shim registered as RuntimeClass "tpu".
//
// TPU-native replacement for the reference's nvidia-container-runtime
// (installed at reference README.md:57-69, consumed via
// `runtimeClassName: nvidia` at values.yaml:4 / nvidia-smi.yaml:8 /
// jellyfin.yaml:23). Like that runtime it is a thin wrapper over runc: on
// `create`/`run` it rewrites the bundle's config.json — bind-mounting
// libtpu.so, adding /dev/accel* (or vfio) device nodes and TPU_* env — then
// execs the real runc. All other commands pass straight through, so
// containerd can use it as a drop-in runtime binary.
//
// Extra subcommand `patch` exposes the rewrite as a standalone operation for
// spec-diff tests and debugging (SURVEY.md §7 step 1).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include <unistd.h>

#include "../common/json.hpp"
#include "spec_patch.hpp"

namespace {

constexpr const char* kVersion = "0.1.0";
constexpr const char* kRuncEnv = "TPU_CONTAINER_RUNTIME_RUNC";
constexpr const char* kConfigPath = "/etc/tpu-container-runtime/config.json";

struct RuntimeConfig {
  std::string runc_path;
  bool always = false;
};

RuntimeConfig load_config() {
  RuntimeConfig cfg;
  if (const char* env = std::getenv(kRuncEnv); env && *env)
    cfg.runc_path = env;
  std::ifstream f(kConfigPath);
  if (f) {
    std::stringstream ss;
    ss << f.rdbuf();
    try {
      auto root = k3stpu::json::parse(ss.str());
      if (cfg.runc_path.empty())
        if (auto p = root->get("runc_path")) cfg.runc_path = p->as_string();
      if (auto a = root->get("always")) cfg.always = a->bool_v;
    } catch (const std::exception& e) {
      // std::exception, not just ParseError: number conversion can throw
      // std::out_of_range (e.g. 1e999), and a bad config file must never
      // wedge every tpu-class container on the node.
      std::cerr << "tpu-container-runtime: bad " << kConfigPath << ": "
                << e.what() << "\n";
    }
  }
  if (cfg.runc_path.empty()) cfg.runc_path = "runc";
  return cfg;
}

std::string read_file(const std::string& path) {
  std::ifstream f(path);
  if (!f) throw std::runtime_error("cannot read " + path);
  std::stringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

void write_file(const std::string& path, const std::string& content) {
  // Write-then-rename so runc never sees a half-written spec.
  const std::string tmp = path + ".tpu-tmp";
  {
    std::ofstream f(tmp, std::ios::trunc);
    if (!f) throw std::runtime_error("cannot write " + tmp);
    f << content;
  }
  if (std::rename(tmp.c_str(), path.c_str()) != 0)
    throw std::runtime_error("cannot rename " + tmp + " -> " + path);
}

// Finds the OCI bundle directory from runc-style argv: `--bundle X`,
// `--bundle=X`, or `-b X`, after the create/run command. Default: cwd.
std::string find_bundle(const std::vector<std::string>& args, size_t cmd_at) {
  for (size_t i = cmd_at; i < args.size(); ++i) {
    const std::string& a = args[i];
    if ((a == "--bundle" || a == "-b") && i + 1 < args.size())
      return args[i + 1];
    if (a.rfind("--bundle=", 0) == 0) return a.substr(9);
  }
  return ".";
}

// Locates the runc command verb, skipping global options and their values.
// Returns args.size() when none found.
size_t find_command(const std::vector<std::string>& args) {
  static const char* opts_with_value[] = {"--log", "--log-format", "--root",
                                          "--criu", "--rootless"};
  for (size_t i = 1; i < args.size(); ++i) {
    const std::string& a = args[i];
    if (a.rfind("-", 0) != 0) return i;
    if (a.find('=') == std::string::npos) {
      for (const char* o : opts_with_value) {
        if (a == o) {
          ++i;
          break;
        }
      }
    }
  }
  return args.size();
}

[[noreturn]] void exec_runc(const RuntimeConfig& cfg,
                            const std::vector<std::string>& args) {
  std::vector<char*> argv;
  std::string argv0 = cfg.runc_path;
  argv.push_back(argv0.data());
  for (size_t i = 1; i < args.size(); ++i)
    argv.push_back(const_cast<char*>(args[i].c_str()));
  argv.push_back(nullptr);
  execvp(cfg.runc_path.c_str(), argv.data());
  std::perror(("tpu-container-runtime: exec " + cfg.runc_path).c_str());
  std::exit(127);
}

int patch_bundle(const std::string& bundle, const k3stpu::runtime::PatchOptions& opts,
                 bool dry_run, bool quiet) {
  const std::string spec_path = bundle + "/config.json";
  auto spec = k3stpu::json::parse(read_file(spec_path));
  auto result = k3stpu::runtime::patch_spec(spec, opts);
  const std::string out = k3stpu::json::dump(spec);
  if (dry_run) {
    std::cout << out;
  } else if (result.injected) {
    write_file(spec_path, out);
  }
  if (!quiet) {
    std::cerr << "tpu-container-runtime: injected=" << result.injected
              << " devices=" << result.n_devices
              << " mounts=" << result.n_mounts << " env=[";
    for (size_t i = 0; i < result.env_added.size(); ++i)
      std::cerr << (i ? "," : "") << result.env_added[i];
    std::cerr << "]\n";
  }
  return 0;
}

int cmd_patch(const std::vector<std::string>& args) {
  k3stpu::runtime::PatchOptions opts;
  std::string bundle = ".";
  bool dry_run = false;
  for (size_t i = 2; i < args.size(); ++i) {
    const std::string& a = args[i];
    if (a == "--bundle" && i + 1 < args.size()) bundle = args[++i];
    else if (a == "--host-root" && i + 1 < args.size()) opts.host_root = args[++i];
    else if (a == "--visible-chips" && i + 1 < args.size())
      opts.visible_chips = args[++i];
    else if (a == "--always") opts.always = true;
    else if (a == "--dry-run") dry_run = true;
    else {
      std::cerr << "tpu-container-runtime patch: unknown option " << a << "\n";
      return 2;
    }
  }
  try {
    return patch_bundle(bundle, opts, dry_run, /*quiet=*/false);
  } catch (const std::exception& e) {
    std::cerr << "tpu-container-runtime patch: " << e.what() << "\n";
    return 1;
  }
}

}  // namespace

int main(int argc, char** argv) {
  std::vector<std::string> args(argv, argv + argc);

  if (args.size() >= 2 && (args[1] == "--version" || args[1] == "-v")) {
    std::cout << "tpu-container-runtime version " << kVersion << "\n";
    return 0;
  }
  if (args.size() >= 2 && args[1] == "patch") return cmd_patch(args);

  RuntimeConfig cfg = load_config();
  size_t cmd_at = find_command(args);
  if (cmd_at < args.size() &&
      (args[cmd_at] == "create" || args[cmd_at] == "run")) {
    const std::string bundle = find_bundle(args, cmd_at);
    try {
      k3stpu::runtime::PatchOptions opts;
      opts.always = cfg.always;
      patch_bundle(bundle, opts, /*dry_run=*/false, /*quiet=*/true);
    } catch (const std::exception& e) {
      // Injection failure must not wedge non-TPU pods; log and continue so
      // the container still starts (matching the reference runtime's
      // pass-through behavior for non-GPU workloads).
      std::cerr << "tpu-container-runtime: patch skipped: " << e.what() << "\n";
    }
  }
  exec_runc(cfg, args);
}
