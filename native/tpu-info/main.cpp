// tpu-info: the nvidia-smi of this stack (SURVEY.md §2b #6).
//
// The reference's first verification step is running `nvidia-smi` on the
// host and reading a device table (reference README.md:71-93); tpu-info is
// that table for TPU hosts — chip inventory from sysfs/devfs, no libtpu or
// python needed, so it also works inside minimal containers and initramfs.
// `--json` emits machine-readable output (what the probe pod parses);
// default is the human table.
//
// Exit code: 0 when at least one chip is visible, 1 when none (script-able
// the way `nvidia-smi` exit codes are), 2 on usage error.

#include <cstdlib>
#include <cstring>
#include <ctime>
#include <iostream>
#include <string>

#include "../common/chips.hpp"
#include "../common/json.hpp"

namespace {

void usage() {
  std::cerr << "tpu-info [--json] [--watch [SECONDS]] [--host-root DIR]\n"
               "  TPU chip inventory from the host PCI/dev tree.\n"
               "  --watch: redraw every SECONDS (default 2), like "
               "`watch nvidia-smi`; ctrl-c exits.\n";
}

// "123MiB / 16384MiB" (nvidia-smi style, reference README.md:78-84); either
// side may be unknown ("n/a / 16384MiB", "1024MiB / n/a"); whole cell "n/a"
// only when both are — live used-bytes must not vanish because the
// generation's total is unreported (v2/v3 report -1).
std::string mem_cell(long long used, long long total,
                     bool estimated = false) {
  if (total < 0 && used < 0) return "n/a";
  auto mib = [](long long b) { return std::to_string(b >> 20) + "MiB"; };
  // '~' marks client-side accounting (drop-file source=live_arrays):
  // an honest lower bound, not allocator truth.
  return (used < 0 ? std::string("n/a")
                   : (estimated ? "~" : "") + mib(used)) +
         " / " + (total < 0 ? std::string("n/a") : mib(total));
}

std::string util_cell(int pct) {
  return pct < 0 ? "n/a" : std::to_string(pct) + "%";
}

int run(const std::string& root, bool as_json) {
  auto chips = k3stpu::enumerate_chips(root);
  k3stpu::fill_telemetry(chips, root);
  auto libtpu = k3stpu::find_libtpu(root);

  if (as_json) {
    using k3stpu::json::Value;
    auto doc = Value::make_object();
    doc->set("chip_count", Value::make_int(static_cast<int64_t>(chips.size())));
    doc->set("topology", Value::make_string(k3stpu::topology_for(chips.size())));
    doc->set("libtpu", Value::make_string(libtpu));
    auto arr = doc->ensure_array("chips");
    for (const auto& c : chips) {
      auto o = Value::make_object();
      o->set("index", Value::make_int(c.index));
      o->set("pci", Value::make_string(c.pci_address));
      o->set("device_id", Value::make_string(c.device_id));
      o->set("generation", Value::make_string(c.generation));
      o->set("numa", Value::make_int(c.numa_node));
      // -1 == unavailable, mirroring the "n/a" cells of the human table.
      o->set("mem_used_bytes", Value::make_int(c.mem_used_bytes));
      o->set("mem_estimated", Value::make_bool(c.mem_estimated));
      o->set("mem_total_bytes", Value::make_int(c.mem_total_bytes));
      o->set("duty_cycle_pct", Value::make_int(c.duty_cycle_pct));
      auto devs = o->ensure_array("dev_paths");
      for (const auto& d : c.dev_paths)
        devs->arr_v.push_back(Value::make_string(d));
      arr->arr_v.push_back(o);
    }
    std::cout << k3stpu::json::dump(doc) << "\n";
  } else {
    const char* rule =
        "+-----+---------------+------------+------+----------------------+"
        "------+-----------------+\n";
    std::cout << "+------------------------------------------------------------"
                 "----------------------------+\n";
    std::cout << "| tpu-info            chips: " << chips.size()
              << "   topology: " << k3stpu::topology_for(chips.size()) << "\n";
    std::cout << "| libtpu: " << (libtpu.empty() ? "(not found)" : libtpu) << "\n";
    std::cout << rule;
    std::cout << "| IDX | PCI           | GENERATION | NUMA | MEMORY           "
                 "    | UTIL | DEV             |\n";
    std::cout << rule;
    for (const auto& c : chips) {
      std::string devs;
      for (const auto& d : c.dev_paths) devs += (devs.empty() ? "" : ",") + d;
      char line[200];
      std::snprintf(line, sizeof(line),
                    "| %3d | %-13s | %-10s | %4d | %-20s | %4s | %-15s |",
                    c.index, c.pci_address.c_str(), c.generation.c_str(),
                    c.numa_node,
                    mem_cell(c.mem_used_bytes, c.mem_total_bytes,
                             c.mem_estimated)
                        .c_str(),
                    util_cell(c.duty_cycle_pct).c_str(), devs.c_str());
      std::cout << line << "\n";
    }
    std::cout << rule;
  }
  return chips.empty() ? 1 : 0;
}

}  // namespace

int main(int argc, char** argv) {
  std::string root;
  bool as_json = false;
  int watch_s = 0;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--json")) {
      as_json = true;
    } else if (!std::strcmp(argv[i], "--host-root") && i + 1 < argc) {
      root = argv[++i];
    } else if (!std::strcmp(argv[i], "--watch")) {
      watch_s = 2;
      if (i + 1 < argc && argv[i + 1][0] != '-') {
        watch_s = std::atoi(argv[++i]);
        if (watch_s <= 0) {
          usage();
          return 2;
        }
      }
    } else if (!std::strcmp(argv[i], "--help") || !std::strcmp(argv[i], "-h")) {
      usage();
      return 0;
    } else {
      usage();
      return 2;
    }
  }
  if (!watch_s) return run(root, as_json);
  // `watch nvidia-smi` is the reference's live-observability idiom
  // (reference README.md:71-93's table, re-read); the telemetry drop file
  // refreshes between draws, so MEMORY/UTIL move while a workload runs.
  while (true) {
    if (!as_json) std::cout << "\033[H\033[2J";  // clear like watch(1)
    run(root, as_json);  // keep watching even while no chips are visible
    std::cout.flush();
    struct timespec ts = {watch_s, 0};
    ::nanosleep(&ts, nullptr);
  }
}
